"""Tusk: zero-message asynchronous BFT commit over the shared DAG.

Reference consensus/src/lib.rs (304 LoC).  Every even round r has a leader;
when the leader of round r−2 gathers f+1 stake support among round r−1
certificates, it commits — together with every preceding uncommitted leader
it is linked to, each flattening its causal sub-DAG in deterministic order.
No extra messages: the commit rule is a pure function of the DAG.

The pure state machine (`Tusk.process_certificate`) is separated from the
async runner (`Consensus`) so the commit rule can be golden-tested directly
and swapped for the JAX adjacency-matrix kernel
(narwhal_tpu/ops/reachability.py) validated certificate-for-certificate
against this implementation.

Commit-path latency model (PR 4 rebuild — the r07 stage breakdown measured
cert→commit at 77% of seal→commit end-to-end latency, and Mysticeti's core
argument is that DAG-consensus latency is won or lost in the commit rule's
reaction time):

- a digest → certificate index rides alongside the round → origin DAG, so
  ``order_dag`` parent resolution and ``linked()`` reachability are O(1)
  per edge instead of a linear scan over a round's certificates per hop;
- leader support accumulates INCREMENTALLY at insert time (a round-(r+1)
  certificate bumps its round-r leader's support counter once), so the
  f+1 gate in ``process_certificate`` is a dict read, not a rescan of the
  whole child round on every odd-round arrival;
- committing updates the frontier per certificate (O(1)) but sweeps the
  DAG window for garbage exactly ONCE per commit burst (``State.gc``) —
  the old per-certificate ``State.update`` full sweep was quadratic in
  burst size;
- the async runner drains its input queue in bursts, processing a backlog
  of queued certificates per wakeup instead of one per task switch.

Every rewrite above is certificate-for-certificate equivalent to the r06
dict walk, which is kept frozen as the oracle in
``narwhal_tpu/consensus/golden.py`` and diffed against on recorded
multi-leader / gc-wrap / checkpoint-restore streams
(tests/test_tusk_equivalence.py).
"""

from __future__ import annotations

import asyncio
import hashlib
import logging
import os
import struct
import tempfile
import time
from typing import Callable, Dict, List, Optional, Tuple

from .. import metrics
from ..config import Committee
from ..crypto import Digest, PublicKey
from ..messages import Round
from ..primary.messages import Certificate, genesis
from ..utils.clock import loop_now

log = logging.getLogger("narwhal.consensus")

# dag: Round → {origin → (certificate digest, certificate)}
Dag = Dict[Round, Dict[PublicKey, Tuple[Digest, Certificate]]]

# The selectable commit rules (NARWHAL_COMMIT_RULE / `node run
# --commit-rule`) and the checkpoint magic each writes.  A frontier
# snapshot is only meaningful to the rule that produced it — the rules
# commit at different depths (and multileader anchors different
# authorities entirely), so one rule's frontier restored under another
# would anchor the walk at rounds that rule never decided.  Distinct
# magics turn that operator error into a LOUD boot-time refusal
# (CheckpointRuleMismatch) instead of a silent reinterpretation.
COMMIT_RULES = ("classic", "lowdepth", "multileader")
RULE_MAGICS = {
    "classic": b"NCKPT1",
    "lowdepth": b"NCKLD1",
    "multileader": b"NCKML1",
}

# Leader slots per even round under the multileader rule.  A pure
# constant (not an env knob): the slot schedule feeds the frozen golden
# oracle and the audit replay judge, so a run-time knob would let a
# replay silently judge a recording against a different schedule.
MULTILEADER_SLOTS = 3


def leader_slots(
    sorted_keys: List[PublicKey],
    round_: Round,
    k: Optional[int] = None,
    fixed_coin: bool = False,
) -> List[PublicKey]:
    """The K leader-slot authorities for an even round, in slot order.

    Deterministic pure function of (sorted committee keys, round) — the
    schedule must be identical across processes and restarts because
    every node's commit decisions and the frozen oracle's replay both
    derive it independently.  Slot 0 ROTATES (``(round // 2) % n``), so
    over any ``committee_size`` consecutive even rounds every authority
    holds slot 0 exactly once — no authority monopolizes the anchor
    slot, and none is starved of it for longer than one full rotation.
    The remaining slots are a round-salted rotation of the rest of the
    committee (SHA-256 of the round number), so the backup slots are
    not permanently the rotation's next-in-line either.

    ``fixed_coin`` pins the schedule to the first K sorted authorities —
    the multileader analogue of the reference's ``#[cfg(test)] coin = 0``
    used by the golden tests."""
    n = len(sorted_keys)
    k = min(n, MULTILEADER_SLOTS if k is None else k)
    if fixed_coin:
        return list(sorted_keys[:k])
    base = (round_ // 2) % n
    order = [sorted_keys[(base + j) % n] for j in range(n)]
    head, rest = order[0], order[1:]
    if len(rest) > 1:
        salt = int.from_bytes(
            hashlib.sha256(struct.pack("<Q", round_)).digest()[:8], "little"
        )
        off = salt % len(rest)
        rest = rest[off:] + rest[:off]
    return [head] + rest[: k - 1]


# Checkpoint cert-sig scheme trailer: 4-byte tag + scheme index,
# appended after the frontier entries.  A frontier is only meaningful
# next to a store the running scheme can replay (the boot-time
# _replay_persisted_certificates feeds the DAG between frontier and
# head back into consensus, and cross-scheme certificates refuse to
# decode) — so a checkpoint written under one scheme refuses to restore
# under the other, in both directions, naming both schemes.  A trailer-
# less checkpoint predates the scheme seam and was necessarily written
# under "individual".
_SCHEME_TRAILER_TAG = b"SCHM"
_SCHEME_TRAILER_LEN = len(_SCHEME_TRAILER_TAG) + 1


def _scheme_trailer() -> bytes:
    from ..crypto.aggregate import SCHEMES, scheme

    return _SCHEME_TRAILER_TAG + bytes([SCHEMES.index(scheme())])


def _check_scheme_trailer(blob: bytes, body_len: int) -> None:
    """Validate a checkpoint's scheme trailer against the running
    scheme.  ``body_len`` is the magic+frontier length; raises
    SchemeMismatch (both names) or ValueError on garbage."""
    from ..crypto.aggregate import SCHEMES, SchemeMismatch, scheme

    if len(blob) == body_len:
        written = "individual"  # pre-scheme checkpoint
    elif (
        len(blob) == body_len + _SCHEME_TRAILER_LEN
        and blob[body_len : body_len + 4] == _SCHEME_TRAILER_TAG
        and blob[-1] < len(SCHEMES)
    ):
        written = SCHEMES[blob[-1]]
    else:
        raise ValueError("checkpoint: truncated or oversized blob")
    if written != scheme():
        raise SchemeMismatch(
            f"checkpoint was written under cert-sig scheme {written!r} "
            f"but this node runs {scheme()!r}; refusing to restore — the "
            "persisted store next to it cannot replay across schemes.  "
            "Wipe the checkpoint+store (and accept re-delivery) or run "
            "the matching --cert-sig-scheme"
        )


class CheckpointRuleMismatch(ValueError):
    """A checkpoint written under one commit rule was offered to the
    other.  Deliberately NOT swallowed by the torn-checkpoint tolerance
    in Consensus boot: booting fresh would silently re-commit (and
    re-deliver) everything the other rule already committed — the
    operator flipped the flag on a live store and must be told."""


def resolve_commit_rule(explicit: Optional[str] = None) -> str:
    """Effective commit rule: the explicit (CLI/constructor) value wins,
    else the NARWHAL_COMMIT_RULE env knob, else classic.  Garbage raises
    — a bench arm must never silently measure the wrong rule (the
    NARWHAL_CRYPTO_BACKEND_STRICT precedent)."""
    from ..utils.env import env_str

    rule = explicit if explicit is not None else env_str("NARWHAL_COMMIT_RULE")
    rule = (rule or "classic").strip().lower()
    if rule not in COMMIT_RULES:
        raise ValueError(
            f"unknown commit rule {rule!r}; expected one of {COMMIT_RULES}"
        )
    return rule


class State:
    """Consensus state (reference lib.rs:19-62), indexed.

    Alongside the reference's round-keyed DAG this keeps
    ``digest_index``: digest → certificate for every certificate currently
    in the DAG (genesis included).  The index is maintained by
    :meth:`insert` and pruned by :meth:`gc`, so membership in the index is
    exactly membership in the DAG — ``order_dag``/``linked`` resolve
    parent digests in O(1) instead of scanning a round dict per lookup.
    """

    def __init__(self, genesis_certs: List[Certificate]) -> None:
        gen = {c.origin: (c.digest(), c) for c in genesis_certs}
        self.last_committed_round: Round = 0
        self.last_committed: Dict[PublicKey, Round] = {
            name: cert.round for name, (_, cert) in gen.items()
        }
        self.dag: Dag = {0: gen}
        self.digest_index: Dict[Digest, Certificate] = {
            d: cert for (d, cert) in gen.values()
        }

    _CKPT_MAGIC = b"NCKPT1"
    commit_rule = "classic"

    def snapshot_bytes(self) -> bytes:
        """Canonical encoding of the committed frontier — the part of
        consensus state that crash-recovery needs (the reference marks
        this persisted-state duty as intended-but-unimplemented,
        consensus/src/lib.rs:18-19; here it IS implemented).  The DAG
        itself is not snapshotted: it is rebuilt by the sync machinery,
        and the restored frontier keeps re-synced history out of the
        commit sequence (see order_dag's skip)."""
        out = bytearray(self._CKPT_MAGIC)
        out += struct.pack("<Q", self.last_committed_round)
        items = sorted(self.last_committed.items())
        out += struct.pack("<I", len(items))
        for name, round in items:
            if len(bytes(name)) != 32:
                raise ValueError("checkpoint: authority key must be 32 bytes")
            out += bytes(name) + struct.pack("<Q", round)
        out += _scheme_trailer()
        return bytes(out)

    def restore(self, blob: bytes) -> None:
        """Seed the committed frontier from snapshot_bytes output.
        Validation raises (never asserts — a malformed blob misparsed
        under ``python -O`` would silently wedge the commit rule at a
        garbage frontier), and the WHOLE blob parses before any state
        mutates: a torn checkpoint must leave the fresh frontier intact
        so the caller can fall back to it (ADVICE.md r05)."""
        if len(blob) >= 6 and blob[:6] != self._CKPT_MAGIC:
            for rule, magic in RULE_MAGICS.items():
                if blob[:6] == magic:
                    raise CheckpointRuleMismatch(
                        f"checkpoint was written by the {rule!r} commit "
                        f"rule but this node runs {self.commit_rule!r}; "
                        "refusing to restore — wipe the checkpoint (and "
                        "accept re-delivery) or run the matching "
                        "--commit-rule"
                    )
        if len(blob) < 18 or blob[:6] != self._CKPT_MAGIC:
            raise ValueError("checkpoint: bad magic")
        (last_round,) = struct.unpack_from("<Q", blob, 6)
        (n,) = struct.unpack_from("<I", blob, 14)
        _check_scheme_trailer(blob, 18 + 40 * n)
        entries = []
        pos = 18
        for _ in range(n):
            name = PublicKey(blob[pos : pos + 32])
            (round,) = struct.unpack_from("<Q", blob, pos + 32)
            entries.append((name, round))
            pos += 40
        self.last_committed_round = last_round
        for name, round in entries:
            self.last_committed[name] = round

    def insert(
        self, certificate: Certificate
    ) -> Tuple[Digest, Optional[Digest]]:
        """Insert into the DAG and digest index.  Returns
        ``(digest, prev_digest)`` where ``prev_digest`` is the digest this
        (round, origin) slot previously held: the same digest for an
        idempotent re-insert (nothing changed), a different digest for an
        equivocation overwrite, or None for a fresh slot — the caller
        (Tusk) uses the distinction to keep its incremental support
        counters exact."""
        d = certificate.digest()
        slot = self.dag.setdefault(certificate.round, {})
        prev = slot.get(certificate.origin)
        if prev is not None and prev[0] == d:
            return d, d
        slot[certificate.origin] = (d, certificate)
        self.digest_index[d] = certificate
        if prev is not None:
            self.digest_index.pop(prev[0], None)
            return d, prev[0]
        return d, None

    def note_committed(self, certificate: Certificate) -> None:
        """O(1) frontier advance for one committed certificate.  The DAG
        sweep is deferred to ONE :meth:`gc` call per commit burst — the
        golden walk's per-certificate full sweep (golden.py
        ``GoldenState.update``) made a K-certificate burst cost K full
        window scans."""
        origin = certificate.origin
        if certificate.round > self.last_committed.get(origin, 0):
            self.last_committed[origin] = certificate.round
        if certificate.round > self.last_committed_round:
            self.last_committed_round = certificate.round

    def gc(self, gc_depth: Round) -> None:
        """One garbage sweep over the window: drop per-authority entries
        strictly below that authority's committed round, whole rounds
        beyond the gc horizon, and empty rounds — pruning the digest
        index in lockstep so index membership stays exactly DAG
        membership.  End-state identical to the golden per-certificate
        sweep (the deferred deletions are all entries the order_dag ≥
        skip already excludes — tests/test_tusk_equivalence.py)."""
        last = self.last_committed_round
        index = self.digest_index
        last_committed = self.last_committed
        for r in list(self.dag):
            authorities = self.dag[r]
            if r + gc_depth < last:
                for d, _ in authorities.values():
                    index.pop(d, None)
                del self.dag[r]
                continue
            dead = [
                name
                for name in authorities
                if r < last_committed.get(name, 0)
            ]
            for name in dead:
                index.pop(authorities[name][0], None)
                del authorities[name]
            if not authorities:
                del self.dag[r]

class LowDepthState(State):
    """State for the lower-depth rule: identical structure, its own
    checkpoint magic (rationale at RULE_MAGICS)."""

    _CKPT_MAGIC = RULE_MAGICS["lowdepth"]
    commit_rule = "lowdepth"


class MultiLeaderState(State):
    """State for the multi-leader rule: identical structure, its own
    checkpoint magic (rationale at RULE_MAGICS)."""

    _CKPT_MAGIC = RULE_MAGICS["multileader"]
    commit_rule = "multileader"


class Tusk:
    """The pure commit rule: feed certificates, get ordered commit batches."""

    STATE_CLS = State
    commit_rule = "classic"

    def __init__(
        self, committee: Committee, gc_depth: Round, fixed_coin: bool = False
    ) -> None:
        self.committee = committee
        self.gc_depth = gc_depth
        # fixed_coin pins the leader to the first authority — the reference's
        # #[cfg(test)] coin = 0 (lib.rs:209-212) used by the golden tests.
        self.fixed_coin = fixed_coin
        self.state = self.STATE_CLS(genesis(committee))
        self._sorted_keys = sorted(committee.authorities.keys())
        # Incremental f+1 support: even leader round → accumulated stake of
        # round+1 certificates citing the leader's digest.  Maintained by
        # insert_certificate; equal at every query point to the golden
        # walk's from-scratch rescan of the child round (the rare
        # equivocation-overwrite path recomputes instead of patching).
        self._support: Dict[Round, int] = {}
        # Optional hook fired from the incremental bump with
        # (leader_round, old_stake, new_stake, supporter) — Consensus
        # attaches its support-arrival-spread and straggler-attribution
        # accounting here (the supporter whose bump crosses the quorum
        # line is the validator that closed it).  Only the hot
        # incremental path fires it: the cold recompute paths
        # (leader-after-supporters, equivocation overwrite) reconstruct
        # stake totals but not arrival ORDER, so they stay silent.
        self.support_observer: Optional[
            Callable[[Round, int, int, PublicKey], None]
        ] = None

    def leader(self, round: Round, dag: Dag) -> Optional[Tuple[Digest, Certificate]]:
        """Round-robin leader (a common coin in the full protocol —
        reference lib.rs:205-221)."""
        return dag.get(round, {}).get(self._leader_name(round))

    def _leader_name(self, round_: Round) -> PublicKey:
        coin = 0 if self.fixed_coin else round_
        return self._sorted_keys[coin % len(self._sorted_keys)]

    def insert_certificate(self, certificate: Certificate) -> None:
        """Insert into the DAG without running the commit rule.  Separate
        seam so KernelTusk can maintain its dense device window
        incrementally, and benchmarks can build large DAG states.  Also
        the single maintenance point for the digest index (via
        State.insert) and the incremental leader-support counters."""
        d, prev = self.state.insert(certificate)
        if prev is not None and prev == d:
            return  # idempotent re-insert: counters already reflect it
        r = certificate.round
        if prev is None:
            # Fresh slot: incremental support accounting.
            if r % 2 == 1 and r >= 3:
                # This certificate may support the leader of round r-1.
                got = self.leader(r - 1, self.state.dag)
                if got is not None and got[0] in certificate.header.parents:
                    old = self._support.get(r - 1, 0)
                    new = old + self.committee.stake(certificate.origin)
                    self._support[r - 1] = new
                    if self.support_observer is not None:
                        self.support_observer(
                            r - 1, old, new, certificate.origin
                        )
            elif (
                r % 2 == 0
                and r >= 2
                and certificate.origin == self._leader_name(r)
            ):
                # The leader itself arrived (possibly after some of its
                # supporters): seed its counter from the children already
                # present — one O(N) scan per leader insert, not per
                # arrival.
                self._recompute_support(r)
        else:
            # Equivocation overwrite (same slot, different digest): the
            # old certificate's contributions are baked into the counters.
            # Rare and adversarial — recompute the affected round exactly.
            if r % 2 == 1 and r >= 3:
                self._recompute_support(r - 1)
            elif (
                r % 2 == 0
                and r >= 2
                and certificate.origin == self._leader_name(r)
            ):
                self._recompute_support(r)

    def _recompute_support(self, leader_round: Round) -> None:
        """From-scratch support for one leader round (the golden rescan,
        used only on the cold paths: leader arriving after supporters, or
        an equivocation overwrite)."""
        got = self.leader(leader_round, self.state.dag)
        if got is None:
            self._support.pop(leader_round, None)
            return
        leader_digest = got[0]
        self._support[leader_round] = sum(
            self.committee.stake(cert.origin)
            for _, cert in self.state.dag.get(leader_round + 1, {}).values()
            if leader_digest in cert.header.parents
        )

    def process_certificate(self, certificate: Certificate) -> List[Certificate]:
        """Insert a certificate; return the newly committed sequence
        (possibly empty).  Reference lib.rs:105-201."""
        state = self.state
        round = certificate.round
        self.insert_certificate(certificate)

        # Order from the highest round with a 2f+1 frontier (needed to
        # reveal the common coin).  Leaders live on even rounds.
        r = round - 1
        if r % 2 != 0 or r < 4:
            return []
        leader_round = r - 2
        if leader_round <= state.last_committed_round:
            return []
        got = self.leader(leader_round, state.dag)
        if got is None:
            return []
        _, leader = got

        # f+1 support among the children (round r-1 certificates) — an
        # O(1) read of the incrementally-accumulated counter.
        if self._support.get(leader_round, 0) < self.committee.validity_threshold():
            log.debug("Leader %r does not have enough support", leader)
            return []

        # Commit every linked uncommitted leader, oldest first, each
        # flattening its causal sub-DAG.  The frontier advances per
        # certificate (order_dag's skip must see it), but the garbage
        # sweep runs ONCE for the whole burst.
        log.debug("Leader %r has enough support", leader)
        sequence: List[Certificate] = []
        for past_leader in reversed(self.order_leaders(leader)):
            for x in self.order_dag(past_leader):
                state.note_committed(x)
                sequence.append(x)
        if sequence:
            state.gc(self.gc_depth)
            # Support for rounds at/below the new frontier can never be
            # queried again (the leader_round <= last_committed_round
            # short-circuit above) — prune so the dict tracks the live
            # window only.
            last = state.last_committed_round
            for lr in [k for k in self._support if k <= last]:
                del self._support[lr]
        return sequence

    def order_leaders(self, leader: Certificate) -> List[Certificate]:
        """The whole linked-leader chain in ONE descending frontier pass
        (reference lib.rs:224-244 walks back two rounds at a time and
        runs a fresh ``linked()`` BFS over the window per earlier leader
        — O(leaders × window)).  The frontier at round r is the causal
        cone of the current chain head; when it reaches the leader of an
        even round, that leader joins the chain and the frontier RESETS
        to it alone — exactly the reference's ``leader = prev_leader``
        rebinding, and exactly the semantics the device kernel's
        ``_chain_scan`` executes (ops/reachability.py), which the r06
        equivalence suite validated certificate-for-certificate.
        Parent digests resolve through the digest index, so each hop is
        O(frontier edges)."""
        state = self.state
        index = state.digest_index
        to_commit = [leader]
        frontier = [leader]
        for r in range(
            leader.round - 1, state.last_committed_round, -1
        ):
            wanted = set()
            for x in frontier:
                wanted.update(x.header.parents)
            frontier = [
                certificate
                for digest in wanted
                if (certificate := index.get(digest)) is not None
                and certificate.round == r
            ]
            if not frontier:
                # Empty causal cone: nothing deeper can be linked.
                break
            if r % 2 == 0:
                got = self.leader(r, state.dag)
                if got is None:
                    continue
                _, prev_leader = got
                if any(
                    x is prev_leader or x == prev_leader for x in frontier
                ):
                    to_commit.append(prev_leader)
                    frontier = [prev_leader]
        return to_commit

    # NOTE: the reference's per-pair ``linked()`` BFS (lib.rs:247-259) has
    # no standalone counterpart here — its reachability question is
    # answered inside order_leaders' single frontier pass (the TPU kernel
    # re-expresses the same loop as boolean adjacency-matrix products).
    # The frozen oracle keeps the original per-pair form (golden.py).

    def order_dag(self, leader: Certificate) -> List[Certificate]:
        """DFS flatten of the leader's causal history, skipping
        already-committed certificates (reference lib.rs:263-303).
        Parent digests resolve through the digest index in O(1); the
        round check preserves the golden walk's only-look-one-round-down
        discipline (a digest present at any other round is not a DAG
        edge)."""
        state = self.state
        index = state.digest_index
        last_committed = state.last_committed
        ordered: List[Certificate] = []
        already_ordered = set()
        buffer = [leader]
        while buffer:
            x = buffer.pop()
            ordered.append(x)
            # Sorted iteration (the reference's BTreeSet order): a Python
            # set's iteration order depends on insertion history, which
            # differs between the author's in-memory header and decoded
            # copies — unsorted DFS would give each node a different
            # intra-round commit order.
            for parent in sorted(x.header.parents):
                certificate = index.get(parent)
                if certificate is None or certificate.round != x.round - 1:
                    continue  # already ordered or GC'd up to here
                skip = parent in already_ordered
                # ≥, not ==: in-process they are equivalent (the gc sweep
                # deletes every DAG entry strictly below an authority's
                # last-committed round, so only the boundary round can
                # still be encountered — the reference's equality check,
                # lib.rs:263-303, relies on exactly that), but after a
                # checkpoint restore the DAG is rebuilt by sync from
                # BEFORE the committed frontier and older rounds reappear;
                # ≥ keeps them out of the sequence.
                skip |= (
                    last_committed.get(certificate.origin, -1)
                    >= certificate.round
                )
                if not skip:
                    buffer.append(certificate)
                    already_ordered.add(parent)
        # Never commit garbage-collected certificates.
        ordered = [
            x
            for x in ordered
            if x.round + self.gc_depth >= state.last_committed_round
        ]
        ordered.sort(key=lambda x: x.round)  # stable: prettier sequence
        return ordered


class LowDepthTusk(Tusk):
    """Mysticeti-style lower-depth commit rule (arXiv:2310.14821),
    layered on the indexed incremental state.

    The classic rule commits the round-L leader when a round-(L+3)
    certificate arrives and f+1 round-(L+1) certificates cite the leader
    — commit depth 3.  This rule commits the leader the moment its
    DIRECT support (round-(L+1) certificates citing it) reaches 2f+1
    stake, i.e. on the odd-round arrival that crosses the threshold (or
    on the leader's own late arrival once its children already carry the
    quorum) — commit depth 1 on the leader itself and ~2 averaged over
    the flattened window, which is where the cert→commit cadence cut
    comes from (97-98% of that latency is commit depth × round period,
    PR 4's attribution).

    Why the stronger 2f+1 gate makes the lower depth safe: once 2f+1
    stake of round-(L+1) certificates cite the leader, ANY certificate
    at round ≥ L+2 has 2f+1 parents at the round below whose
    intersection with the support set carries f+1 stake — so every later
    anchor is provably linked to this leader, and a node that never ran
    the direct path (it committed a later anchor first) orders this
    leader at exactly the same position through the INDIRECT path: the
    inherited ``order_leaders`` chain walk, whose linked/skip decisions
    are a pure function of the DAG because Core only delivers causally
    complete certificates.  Skipped leaders (support forever < 2f+1 and
    unlinked) stay skipped on every node for the same reason.

    Commit sequences DIFFER from Tusk by design, so this rule is judged
    against its own frozen oracle (``consensus/golden_lowdepth.py``),
    never against GoldenTusk; checkpoints carry the ``NCKLD1`` magic and
    refuse a cross-rule restore.  The support counters, index, GC and
    flatten are all the inherited PR 4 machinery — only the decision
    gate and the trigger shape differ."""

    STATE_CLS = LowDepthState
    commit_rule = "lowdepth"

    def process_certificate(self, certificate: Certificate) -> List[Certificate]:
        state = self.state
        round = certificate.round
        self.insert_certificate(certificate)

        # Which leader can this arrival have affected?  Odd-round
        # certificates add direct support for their round-(r-1) leader
        # (insert_certificate just bumped the counter); the round-r
        # leader itself arriving makes already-present support countable
        # (the counter was just seeded).  Anything else cannot change a
        # direct-commit decision and returns without walking.
        if round % 2 == 1:
            leader_round = round - 1
        elif certificate.origin == self._leader_name(round):
            leader_round = round
        else:
            return []
        if leader_round < 2 or leader_round <= state.last_committed_round:
            return []
        got = self.leader(leader_round, state.dag)
        if got is None:
            return []
        _, leader = got

        # DIRECT gate: 2f+1 support — an O(1) read of the same
        # incrementally-accumulated counter the classic rule reads at
        # f+1 (class docstring for why the stronger quorum is what buys
        # the lower depth).
        if self._support.get(leader_round, 0) < self.committee.quorum_threshold():
            return []

        log.debug("Leader %r has direct 2f+1 support", leader)
        sequence: List[Certificate] = []
        for past_leader in reversed(self.order_leaders(leader)):
            for x in self.order_dag(past_leader):
                state.note_committed(x)
                sequence.append(x)
        if sequence:
            state.gc(self.gc_depth)
            last = state.last_committed_round
            for lr in [k for k in self._support if k <= last]:
                del self._support[lr]
        return sequence


class MultiLeaderTusk(Tusk):
    """Mysticeti-style multi-leader commit rule (arXiv:2310.14821 §4,
    "multiple leaders per round"), layered on the indexed state.

    One leader per even round leaves the commit cadence hostage to one
    validator's support-arrival luck: the lowdepth rule's 2.05× win at
    N=4 collapses to ~1.0–1.3× at N=10/20 because a header's parents
    are exactly the FIRST 2f+1 certificates of the round (the
    round-advance quorum), so each round-(L+1) certificate cites the
    round-L leader with probability ≈ 2/3 and the leader's direct
    support hovers AT the quorum line (artifacts/commit_rule_ab_r20.json
    caveat).  This rule gives every even round K = ``MULTILEADER_SLOTS``
    leader slots (schedule: :func:`leader_slots`) so any supported slot
    can anchor the round's commit, and pairs with the Proposer's
    ``header_linger_ms`` knob, which widens parent sets past the bare
    quorum so slot support stops being borderline.

    Decision rules (all pure functions of the DAG, which is what makes
    the commit sequence a cross-node-consistent prefix — the same
    property the other two rules lean on):

    - **direct support**: stake of round-(L+1) certificates citing slot
      s's leader digest, accumulated INCREMENTALLY per (round, slot) at
      insert time — the per-leader counters of the classic rule,
      extended per-slot.
    - **dead slot**: ≥ 2f+1 stake of round-(L+1) certificates exist
      that do NOT cite the slot leader.  Final and view-independent: at
      most f stake of child certificates remain unseen, so the slot's
      support can never reach 2f+1 anywhere.
    - **direct anchor**: the commit scan walks slots 0..K-1 in order
      and anchors on the LOWEST slot whose support reaches 2f+1, but
      only if every lower slot is dead — a lower slot that is merely
      *undecided* (neither 2f+1 support nor 2f+1 non-support yet) could
      still anchor on another node, so acting past it would fork the
      sequence.  Two nodes that direct-anchor the same round therefore
      anchor the SAME slot: slot s anchoring here means every lower
      slot has ≤ f support, while slot t < s anchoring elsewhere would
      need 2f+1 — impossible in one 3f+1-stake child round.
    - **indirect (chain walk)**: while descending the committed chain,
      the member for even round r is the first slot whose leader has
      f+1 stake of supporters INSIDE the walk frontier (the causal cone
      of the nearest committed anchor above — Mysticeti's "indirect
      decision via the first committed anchor", which is what makes it
      identical on every node).  A direct-anchored slot always
      re-derives: its 2f+1 supporters intersect the ≥ 2f+1-stake cone
      at every round in f+1 stake, while dead lower slots (≤ f global
      support) can never reach f+1 cone support.

    The anchor's causal sub-DAG is ordered exactly as today: the
    inherited ``order_dag`` flatten, ``note_committed`` frontier
    advance, and one ``State.gc`` sweep per burst.  Commit sequences
    DIFFER from both other rules by design, so this rule is judged
    against its own frozen oracle (``consensus/golden_multileader.py``);
    checkpoints carry the ``NCKML1`` magic and refuse a cross-rule
    restore."""

    STATE_CLS = MultiLeaderState
    commit_rule = "multileader"

    def __init__(
        self, committee: Committee, gc_depth: Round, fixed_coin: bool = False
    ) -> None:
        super().__init__(committee, gc_depth, fixed_coin=fixed_coin)
        # (even leader round, slot) → accumulated stake of round+1
        # certificates citing that slot leader's digest.  The base
        # class's single-leader ``_support`` dict stays empty (this
        # class overrides both maintenance points).
        self._slot_support: Dict[Tuple[Round, int], int] = {}
        # even leader round → accumulated stake of round+1 certificates
        # present at all (the denominator of the dead-slot rule).
        self._child_stake: Dict[Round, int] = {}
        # round → slot schedule; rebuilt on demand, pruned with the
        # counters (one SHA-256 per round otherwise recomputed per
        # child-certificate insert).
        self._slot_cache: Dict[Round, List[PublicKey]] = {}
        # (leader_round, anchor_slot) of the most recent direct anchor —
        # the runner annotates the commit flight event with it so a
        # missed-slot round is readable on the Perfetto timeline.
        self.last_anchor: Optional[Tuple[Round, int]] = None

    def _slots(self, round_: Round) -> List[PublicKey]:
        slots = self._slot_cache.get(round_)
        if slots is None:
            slots = leader_slots(
                self._sorted_keys, round_, fixed_coin=self.fixed_coin
            )
            self._slot_cache[round_] = slots
        return slots

    def insert_certificate(self, certificate: Certificate) -> None:
        d, prev = self.state.insert(certificate)
        if prev is not None and prev == d:
            return  # idempotent re-insert: counters already reflect it
        r = certificate.round
        dag = self.state.dag
        if prev is None:
            if r % 2 == 1 and r >= 3:
                # Fresh child certificate: count it once toward the
                # round's child stake, and toward every slot leader it
                # cites — the classic incremental bump, per slot.
                stake = self.committee.stake(certificate.origin)
                self._child_stake[r - 1] = (
                    self._child_stake.get(r - 1, 0) + stake
                )
                slot_row = dag.get(r - 1, {})
                parents = certificate.header.parents
                for s, name in enumerate(self._slots(r - 1)):
                    got = slot_row.get(name)
                    if got is not None and got[0] in parents:
                        old = self._slot_support.get((r - 1, s), 0)
                        new = old + stake
                        self._slot_support[(r - 1, s)] = new
                        if s == 0 and self.support_observer is not None:
                            # Slot 0 is the round's primary anchor slot:
                            # its quorum spread is what
                            # consensus.support_arrival_ms prices, same
                            # clock and semantics as the other rules.
                            self.support_observer(
                                r - 1, old, new, certificate.origin
                            )
            elif r % 2 == 0 and r >= 2 and certificate.origin in set(
                self._slots(r)
            ):
                # A slot leader arrived (possibly after some of its
                # supporters): seed its counter from the children
                # already present.
                self._recompute_slot_support(r)
        else:
            # Equivocation overwrite: recompute the affected round
            # exactly (rare and adversarial, same policy as the base).
            if r % 2 == 1 and r >= 3:
                self._recompute_slot_support(r - 1)
            elif r % 2 == 0 and r >= 2 and certificate.origin in set(
                self._slots(r)
            ):
                self._recompute_slot_support(r)

    def _recompute_slot_support(self, leader_round: Round) -> None:
        """From-scratch per-slot support and child stake for one leader
        round (cold paths only: a slot leader arriving after supporters,
        or an equivocation overwrite)."""
        dag = self.state.dag
        slot_row = dag.get(leader_round, {})
        children = dag.get(leader_round + 1, {}).values()
        stakes = [
            (self.committee.stake(cert.origin), cert.header.parents)
            for _, cert in children
        ]
        self._child_stake[leader_round] = sum(s for s, _ in stakes)
        for s, name in enumerate(self._slots(leader_round)):
            got = slot_row.get(name)
            if got is None:
                self._slot_support.pop((leader_round, s), None)
                continue
            digest = got[0]
            self._slot_support[(leader_round, s)] = sum(
                stake for stake, parents in stakes if digest in parents
            )

    def _direct_anchor(
        self, leader_round: Round
    ) -> Optional[Tuple[Certificate, int]]:
        """Slot-ordered anchor scan: the lowest slot with 2f+1 direct
        support, provided every lower slot is provably dead (class
        docstring).  Returns (anchor certificate, slot) or None."""
        quorum = self.committee.quorum_threshold()
        child_stake = self._child_stake.get(leader_round, 0)
        slot_row = self.state.dag.get(leader_round, {})
        for s, name in enumerate(self._slots(leader_round)):
            support = self._slot_support.get((leader_round, s), 0)
            if support >= quorum:
                got = slot_row.get(name)
                if got is None:
                    # Supporters cite a digest this DAG no longer holds
                    # (equivocation overwrite race) — not anchorable.
                    return None
                return got[1], s
            if child_stake - support < quorum:
                # Undecided slot: it may still reach quorum, so no
                # higher slot may anchor past it yet.
                return None
            # Dead slot (≤ f stake can ever cite it): scan on.
        return None

    def _cone_member(
        self, leader_round: Round, frontier: List[Certificate]
    ) -> Optional[Certificate]:
        """Chain member for an even round during the descent: the first
        slot whose leader has f+1 stake of supporters among the frontier
        (= the causal cone of the nearest committed anchor above, at
        round leader_round+1) — the indirect decision, identical on
        every node because the cone is a pure function of the DAG."""
        validity = self.committee.validity_threshold()
        slot_row = self.state.dag.get(leader_round, {})
        for name in self._slots(leader_round):
            got = slot_row.get(name)
            if got is None:
                continue
            digest = got[0]
            support = sum(
                self.committee.stake(x.origin)
                for x in frontier
                if digest in x.header.parents
            )
            if support >= validity:
                return got[1]
        return None

    def order_leaders(self, leader: Certificate) -> List[Certificate]:
        """Same single descending frontier pass as the base walk, but
        the even-round membership test is the per-slot cone decision
        (``_cone_member``) instead of the fixed single-leader lookup."""
        state = self.state
        index = state.digest_index
        to_commit = [leader]
        frontier = [leader]
        fr = leader.round
        while fr - 1 > state.last_committed_round:
            wanted = set()
            for x in frontier:
                wanted.update(x.header.parents)
            nxt = [
                certificate
                for digest in wanted
                if (certificate := index.get(digest)) is not None
                and certificate.round == fr - 1
            ]
            if not nxt:
                # Empty causal cone: nothing deeper can be linked.
                break
            frontier = nxt
            fr -= 1
            if fr % 2 == 1 and fr - 1 > state.last_committed_round:
                # The frontier sits at the child round of even round
                # fr-1: decide that round's chain member inside it.
                member = self._cone_member(fr - 1, frontier)
                if member is not None:
                    to_commit.append(member)
                    frontier = [member]
                    fr -= 1
        return to_commit

    def process_certificate(self, certificate: Certificate) -> List[Certificate]:
        state = self.state
        round = certificate.round
        self.insert_certificate(certificate)

        # Which leader round can this arrival have affected?  Odd-round
        # certificates change slot support / child stake for round r-1
        # (both the quorum and the dead-slot side of the scan); a slot
        # leader's own arrival makes already-present support countable.
        if round % 2 == 1:
            leader_round = round - 1
        elif certificate.origin in set(self._slots(round)):
            leader_round = round
        else:
            return []
        if leader_round < 2 or leader_round <= state.last_committed_round:
            return []

        anchor = self._direct_anchor(leader_round)
        if anchor is None:
            return []
        leader, slot = anchor
        self.last_anchor = (leader_round, slot)

        log.debug(
            "Slot %d leader %r has direct 2f+1 support", slot, leader
        )
        sequence: List[Certificate] = []
        for past_leader in reversed(self.order_leaders(leader)):
            for x in self.order_dag(past_leader):
                state.note_committed(x)
                sequence.append(x)
        if sequence:
            state.gc(self.gc_depth)
            last = state.last_committed_round
            for key in [k for k in self._slot_support if k[0] <= last]:
                del self._slot_support[key]
            for lr in [k for k in self._child_stake if k <= last]:
                del self._child_stake[lr]
            for lr in [k for k in self._slot_cache if k <= last]:
                del self._slot_cache[lr]
        return sequence


def _sweep_checkpoint_tmps(checkpoint_path: str) -> None:
    """Unlink `<basename>.tmp.*` leftovers beside the checkpoint (boot
    only; see the call site in Consensus.__init__)."""
    directory = os.path.dirname(checkpoint_path) or "."
    prefix = os.path.basename(checkpoint_path) + ".tmp."
    try:
        entries = os.listdir(directory)
    except OSError:
        return  # directory missing: the writer will report it per burst
    for name in entries:
        if name.startswith(prefix):
            try:
                os.unlink(os.path.join(directory, name))
            except OSError:
                pass


class Consensus:
    """Async runner: certificates in from the primary, ordered certificates
    out to the application and back to the primary for GC."""

    # Upper bound on certificates drained per wakeup: keeps one flood from
    # monopolizing the loop while still collapsing a backlog into one
    # scheduling slice.
    MAX_DRAIN = 256

    def __init__(
        self,
        committee: Committee,
        gc_depth: Round,
        rx_primary: asyncio.Queue,
        tx_primary: asyncio.Queue,
        tx_output: asyncio.Queue,
        benchmark: bool = False,
        fixed_coin: bool = False,
        use_kernel: bool = False,
        checkpoint_path: Optional[str] = None,
        audit_path: Optional[str] = None,
        commit_rule: Optional[str] = None,
    ) -> None:
        # Commit-rule selection (constructor arg > NARWHAL_COMMIT_RULE >
        # classic) happens HERE so every harness that builds a Consensus
        # rides the same resolution the node CLI does.
        rule = resolve_commit_rule(commit_rule)
        self.commit_rule = rule
        if use_kernel:
            if rule != "classic":
                raise ValueError(
                    "--experimental-consensus-kernel implements the "
                    "classic walk only; it cannot run commit rule "
                    f"{rule!r}"
                )
            # Deferred: the pure-CPU node path must not pay the JAX import.
            from ..ops.reachability import KernelTusk

            self.tusk = KernelTusk(committee, gc_depth, fixed_coin=fixed_coin)
        elif rule == "lowdepth":
            self.tusk = LowDepthTusk(committee, gc_depth, fixed_coin=fixed_coin)
        elif rule == "multileader":
            self.tusk = MultiLeaderTusk(
                committee, gc_depth, fixed_coin=fixed_coin
            )
        else:
            self.tusk = Tusk(committee, gc_depth, fixed_coin=fixed_coin)
        self.rx_primary = rx_primary
        self.tx_primary = tx_primary
        self.tx_output = tx_output
        self.benchmark = benchmark
        self._m_certs_in = metrics.counter("consensus.certificates_in")
        self._m_commits = metrics.counter("consensus.committed_certificates")
        self._m_batches = metrics.counter("consensus.committed_batch_digests")
        self._m_commit_batch = metrics.histogram(
            "consensus.commit_batch_size", metrics.COUNT_BUCKETS
        )
        # Commit-path attribution (PR 4): how long one triggering
        # process_certificate call takes (insert + chain walk + flatten),
        # and how many queued certificates each runner wakeup drains.
        self._m_walk = metrics.histogram("consensus.commit_walk_seconds")
        self._m_drain = metrics.histogram(
            "consensus.drain_batch_size", metrics.COUNT_BUCKETS
        )
        # Per-certificate insert→commit latency on the LOOP clock
        # (``loop_now``): wall-identical to the trace sub-legs on a live
        # node, but VIRTUAL under the simulation — which is what lets a
        # sim flag-flip sweep price a commit-rule latency claim in
        # protocol time before any socketed run.  The timestamp map is
        # pure metrics bookkeeping, so it is skipped entirely when the
        # registry is disabled.
        self._m_c2c = metrics.histogram("consensus.cert_to_commit_seconds")
        self._c2c_on = metrics.registry().enabled
        self._insert_ts: Dict[bytes, Tuple[Round, float]] = {}
        self._insert_head: Round = 0
        # Sweep trigger for the timestamp map: twice the steady-state
        # ceiling (one cert per (round, authority) inside the GC window).
        # Under it, commits pop entries and the sweep never runs; a
        # stalled-but-receiving node crosses it and gets pruned back.
        self._c2c_cap = 2 * gc_depth * len(committee.authorities)
        self._m_round = metrics.gauge("consensus.last_committed_round")
        self._m_lag = metrics.gauge("consensus.commit_lag_rounds")
        self._mtrace = metrics.trace()
        # Support-arrival spread: per leader round, the loop-clock span
        # from the FIRST direct supporter landing to the arrival that
        # crossed the 2f+1 quorum line — how long a lower-depth commit
        # rule would wait past first contact (the multi-leader flip's
        # before-number).  Driven from Tusk's incremental support bump,
        # so it measures arrival ORDER on the same clock cert_to_commit
        # uses: wall time on a live node, virtual time under the sim.
        self._m_support_arrival = metrics.histogram(
            "consensus.support_arrival_ms", metrics.LATENCY_MS_BUCKETS
        )
        self._support_first: Dict[Round, float] = {}
        # Support-quorum straggler attribution: the validator whose
        # direct-support bump crossed the 2f+1 line CLOSED that leader's
        # support quorum — count it by primary address, so metrics_check
        # can rank "which validator's luck gates the lowdepth rule"
        # committee-wide (the gap itself is support_arrival_ms above).
        self._m_support_straggler = {
            n: metrics.counter(
                f"consensus.support_straggler."
                f"{a.primary.primary_to_primary}"
            )
            for n, a in committee.authorities.items()
        }
        # Multileader anchor-slot distribution: which slot index anchored
        # each direct commit.  Slot 0 dominating means the primary slot
        # is healthy; weight on higher slots means the backup slots are
        # earning their keep (a dead/undecided slot 0 was skipped).
        self._m_anchor_slot = (
            {
                s: metrics.counter(f"consensus.anchor_slot.{s}")
                for s in range(MULTILEADER_SLOTS)
            }
            if rule == "multileader"
            else {}
        )
        if self._c2c_on:
            _quorum = committee.quorum_threshold()

            def _observe_support(
                leader_round: Round,
                old_stake: int,
                new_stake: int,
                supporter: PublicKey,
            ) -> None:
                now = loop_now()
                first = self._support_first.setdefault(leader_round, now)
                if old_stake < _quorum <= new_stake:
                    self._m_support_arrival.observe(1000.0 * (now - first))
                    counter = self._m_support_straggler.get(supporter)
                    if counter is not None:
                        counter.inc()

            self.tusk.support_observer = _observe_support
        # Crash-recovery of the committed frontier (beyond reference
        # parity — it leaves consensus state unpersisted,
        # consensus/src/lib.rs:18-19).  The checkpoint is its own small
        # file rewritten atomically (write-temp + os.replace), NOT a
        # record in the append-only store log — only the latest frontier
        # is live, so appending one per commit batch would grow the log
        # and every boot-time replay without bound.  What it buys a
        # restarted node: order_leaders and the GC filter anchor at the
        # true frontier instead of round 0, and pre-crash certificates
        # replayed INTO consensus (a lagging peer's catch-up flood routed
        # through the Core) stay out of the commit sequence (order_dag's
        # ≥ skip) — demonstrated directly in tests/test_consensus.py::
        # test_checkpoint_restore_resumes_without_redelivery.  (On a
        # store-preserving restart with healthy peers, history doesn't
        # reach consensus at all — the persisted header/cert store
        # satisfies dependency checks without replay — so the checkpoint
        # is the backstop for the paths where it does.)
        self.checkpoint_path = checkpoint_path
        if checkpoint_path is not None:
            # Sweep tmp files stranded by a crash between mkstemp and
            # os.replace (unique names are what make concurrent writers
            # safe, but uniqueness also means nothing reuses a stranded
            # one — without this, a crash-looping node grows one stale
            # tmp per incarnation forever).  Only OUR basename's tmps;
            # a concurrently-running sibling instance would have to be
            # mid-write on the same path to lose one, which the unique
            # names exist to make harmless anyway (it retries next
            # burst).
            _sweep_checkpoint_tmps(checkpoint_path)
        restored_blob = b""
        if checkpoint_path is not None and os.path.exists(checkpoint_path):
            try:
                with open(checkpoint_path, "rb") as f:
                    blob = f.read()
                self.tusk.state.restore(blob)
                restored_blob = blob
            except CheckpointRuleMismatch:
                # The ONE restore failure that must not fall back to a
                # fresh frontier: the file is a healthy checkpoint from
                # the OTHER commit rule (operator flipped the flag on a
                # live store).  Booting fresh would silently replay and
                # re-commit everything the other rule already delivered
                # — refuse instead, naming the fix.
                log.exception(
                    "Checkpoint %s belongs to the other commit rule; "
                    "REFUSING to boot (this node runs %r)",
                    checkpoint_path, rule,
                )
                raise
            except Exception:
                # A torn/corrupt checkpoint must not crash-loop the node:
                # the file is a recovery OPTIMIZATION (restore validates
                # before mutating, so the fresh frontier below is intact).
                # Booting fresh is always safe — at worst already-committed
                # certificates re-deliver, dedupable downstream by digest.
                log.exception(
                    "Checkpoint %s is corrupt or torn; IGNORING it and "
                    "booting from a fresh consensus frontier",
                    checkpoint_path,
                )
            else:
                if hasattr(self.tusk, "_win_shift"):
                    # Realign the kernel's dense window to the restored
                    # frontier (slot 0 == last_committed_round).
                    self.tusk._win_shift()
                log.info(
                    "Restored consensus frontier at round %d",
                    self.tusk.state.last_committed_round,
                )
        # Fault-suite audit segment (consensus/replay.py): every inserted
        # certificate and every committed digest, for golden-oracle replay
        # — the safety verdict's raw material.  One segment per process
        # incarnation; the restore marker anchors the oracle at the same
        # frontier this instance booted with.
        self._audit = None
        if audit_path:
            from .replay import AuditWriter

            self._audit = AuditWriter(audit_path)
            self._audit.restore_marker(restored_blob)
            # The rule marker makes every segment self-describing: the
            # replay judge picks the matching frozen oracle per segment
            # (GoldenTusk / GoldenLowDepthTusk / GoldenMultiLeaderTusk)
            # instead of assuming a process-wide flag — a flag-flip
            # sweep's arms then judge themselves correctly with no
            # harness plumbing.
            self._audit.rule_marker(rule)
            self._audit.flush()

    async def run(self) -> None:
        while True:
            # Burst-drain: one wakeup processes the whole backlog (a sync
            # release, a slow scheduling slice on a shared core, or a
            # catch-up flood queues many certificates), instead of paying
            # one task switch per certificate.
            batch = [await self.rx_primary.get()]
            while len(batch) < self.MAX_DRAIN:
                try:
                    batch.append(self.rx_primary.get_nowait())
                except asyncio.QueueEmpty:
                    break
            self._m_drain.observe(len(batch))
            committed_any = False
            loop_ts = loop_now()
            for certificate in batch:
                self._m_certs_in.inc()
                if self._c2c_on:
                    self._insert_ts.setdefault(
                        bytes(certificate.digest()),
                        (certificate.round, loop_ts),
                    )
                    if certificate.round > self._insert_head:
                        self._insert_head = certificate.round
                if self._audit is not None:
                    self._audit.insert(certificate)
                # cert_inserted: the certificate's payload entered the
                # commit rule's state — the start of the cert→commit
                # sub-span attribution.
                if certificate.header.payload:
                    now = time.time()
                    for digest in certificate.header.payload:
                        self._mtrace.mark(
                            bytes(digest).hex(), "cert_inserted", ts=now
                        )
                t0 = time.time()
                sequence = self.tusk.process_certificate(certificate)
                t_walk = time.time()
                state = self.tusk.state
                # Committed-certificate lag: how far the DAG head has run
                # ahead of the committed frontier.  A steadily growing lag
                # means the commit rule is starved (missing leader
                # support) while certificates keep arriving.
                self._m_lag.set(
                    max(0, certificate.round - state.last_committed_round)
                )
                self._m_round.set(state.last_committed_round)
                if sequence:
                    committed_any = True
                    self._m_commits.inc(len(sequence))
                    self._m_commit_batch.observe(len(sequence))
                    self._m_walk.observe(t_walk - t0)
                    # Flight-ring landmark: one event per commit burst
                    # (not per cert — bursts are the protocol unit and
                    # the ring is bounded).  Under the multileader rule
                    # the burst also carries its anchor (leader round +
                    # slot index) and that round's slot schedule, so the
                    # Perfetto export can show which slot anchored and
                    # which slots were passed over.
                    extra = {}
                    anchor = getattr(self.tusk, "last_anchor", None)
                    if anchor is not None:
                        anchor_round, anchor_slot = anchor
                        extra = {
                            "anchor_round": anchor_round,
                            "anchor_slot": anchor_slot,
                            "slots": ",".join(
                                bytes(name).hex()[:8]
                                for name in self.tusk._slots(anchor_round)
                            ),
                        }
                        counter = self._m_anchor_slot.get(anchor_slot)
                        if counter is not None:
                            counter.inc()
                    metrics.flight_event(
                        "commit",
                        certs=len(sequence),
                        batches=sum(
                            len(c.header.payload) for c in sequence
                        ),
                        round=state.last_committed_round,
                        walk_ms=round(1000 * (t_walk - t0), 2),
                        **extra,
                    )
                if sequence:
                    commit_ts = loop_now()
                    for committed in sequence:
                        entry = self._insert_ts.pop(
                            bytes(committed.digest()), None
                        )
                        if entry is not None:
                            self._m_c2c.observe(commit_ts - entry[1])
                for committed in sequence:
                    if self._audit is not None:
                        self._audit.commit(committed)
                    header = committed.header
                    self._m_batches.inc(len(header.payload))
                    for digest in header.payload:
                        h = bytes(digest).hex()
                        # commit_trigger: the arrival that fired the
                        # commit rule (cadence boundary); walk_done: the
                        # chain walk + flatten finished (walk cost).
                        self._mtrace.mark(h, "commit_trigger", ts=t0)
                        self._mtrace.mark(h, "walk_done", ts=t_walk)
                    if self.benchmark and header.payload:
                        for digest in header.payload:
                            # Parsed by the benchmark log parser (reference
                            # lib.rs:185-189).
                            log.info(
                                "Committed B%d(%r) -> %r",
                                header.round,
                                header.id,
                                digest,
                            )
                    else:
                        log.info("Committed B%d(%r)", header.round, header.id)
                    await self.tx_primary.put(committed)
                    await self.tx_output.put(committed)
                    if header.payload:
                        # commit: delivered downstream (the remaining leg
                        # is queue/backpressure, not protocol).
                        now = time.time()
                        for digest in header.payload:
                            self._mtrace.mark(
                                bytes(digest).hex(), "commit", ts=now
                            )
            if self._c2c_on and len(self._insert_ts) > self._c2c_cap:
                # Prune timestamps the DAG head has outrun — keyed on the
                # HEAD round, not the committed frontier, so the map
                # stays bounded even on a node whose commit rule is
                # stalled (partitioned minority, leader-support drought)
                # while certificates keep arriving.  A pruned certificate
                # that later commits just loses its latency sample (the
                # pop above tolerates a miss).
                horizon = self._insert_head - self.tusk.gc_depth
                if horizon > 0:
                    for d in [
                        d
                        for d, (r, _) in self._insert_ts.items()
                        if r < horizon
                    ]:
                        del self._insert_ts[d]
            if self._c2c_on and len(self._support_first) > self._c2c_cap:
                # Same horizon logic as _insert_ts: first-arrival stamps
                # for leader rounds the DAG head has outrun can never
                # see another supporter (those inserts are GC-dropped).
                horizon = self._insert_head - self.tusk.gc_depth
                if horizon > 0:
                    for lr in [
                        lr for lr in self._support_first if lr < horizon
                    ]:
                        del self._support_first[lr]
            if self._audit is not None:
                # One flush per drained burst: the burst's 'I' and 'C'
                # records land (or tear) together, which is what lets the
                # replayer treat a torn tail as a clean prefix.
                self._audit.flush()
            if committed_any and self.checkpoint_path is not None:
                # One atomic rewrite per drained burst, AFTER delivery: a
                # crash in the window re-delivers at most this burst on
                # restart (at-least-once at the boundary, dedupable by
                # certificate digest downstream) instead of silently
                # LOSING it, which nothing downstream could repair.
                # The write+fsync runs in the default executor: an fsync
                # on the event loop blocked the ENTIRE primary process
                # (proposer, core) for the disk's flush latency per
                # commit burst — commit-path work slowing round cadence
                # itself.  Awaiting here still serializes rewrites within
                # this task (no torn interleavings), and the checkpoint's
                # crash-recovery semantics tolerate the added staleness
                # (it is an optimization; at worst one more burst
                # re-delivers).
                blob = self.tusk.state.snapshot_bytes()
                try:
                    await asyncio.get_running_loop().run_in_executor(
                        None, self._write_checkpoint, blob
                    )
                except OSError:
                    # The checkpoint is a recovery OPTIMIZATION: a failed
                    # rewrite (ENOSPC clearing, a tmp-dir hiccup, a
                    # racing writer) costs one burst of at-least-once
                    # re-delivery on the next restart — an unhandled
                    # exception here killed the ENTIRE commit pipeline
                    # instead, silently wedging the node while certs
                    # kept queueing.  Found by the narwhal-race
                    # deterministic harness (ISSUE 10): a restart
                    # overlap made the pre-crash incarnation's in-flight
                    # executor write race this one's and the loser's
                    # os.replace raised FileNotFoundError straight into
                    # Consensus.run.
                    log.exception(
                        "consensus checkpoint rewrite to %s failed; "
                        "continuing without it (next burst retries)",
                        self.checkpoint_path,
                    )

    def _write_checkpoint(self, blob: bytes) -> None:
        # Unique tmp per write (NOT a fixed `<path>.tmp`): two writers
        # sharing one checkpoint path — an in-process restart whose
        # previous incarnation's executor write is still in flight, or
        # two instances pointed at one file — would open the same tmp
        # and the loser's os.replace would find it already renamed away.
        # With unique tmps, concurrent writers are safe: os.replace is
        # atomic, last-completed-writer wins, and the file under the
        # final name is always a complete snapshot.
        fd, tmp = tempfile.mkstemp(
            dir=os.path.dirname(self.checkpoint_path) or ".",
            prefix=os.path.basename(self.checkpoint_path) + ".tmp.",
        )
        try:
            with os.fdopen(fd, "wb") as f:
                f.write(blob)
                # fsync BEFORE the rename: os.replace is atomic against
                # process crash, but on power loss the rename can become
                # durable before the data, leaving a torn file under the
                # final name (ADVICE.md r05).
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self.checkpoint_path)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
