"""The r06 dict-walk Tusk, kept verbatim as a test/bench oracle.

This module is a frozen copy of the pre-index commit rule
(narwhal_tpu/consensus/tusk.py as of PR 3): every parent lookup in
``order_dag`` is a linear scan over a round's certificates, ``linked()``
does per-hop list-membership checks, leader support is recomputed from
scratch on every odd-round arrival, and ``State.update`` sweeps the whole
DAG once per committed certificate.  Slow — and *known correct*: it is
the implementation the reference scenarios (consensus_tests.rs) were
golden-tested against for six rounds.

The live ``Tusk`` rebuilt around indexed, incremental state (PR 4) must
stay certificate-for-certificate equivalent to THIS walk; the discipline
follows the "Reusable Formal Verification of DAG-based Consensus
Protocols" observation (PAPERS.md) that every commit-rule rewrite needs
an unchanged oracle to diff against.  Consumers:

- tests/test_tusk_equivalence.py replays recorded certificate streams
  (multi-leader burst, gc-window wrap, checkpoint restore, fuzz) through
  both implementations and asserts byte-identical commit sequences;
- bench_consensus.py's commit-burst phase uses it as the "before" arm of
  the indexed-walk speedup table (artifacts/consensus_bench_r09.json).

Do not optimize this file.  Its only job is to stay what it was.
"""

from __future__ import annotations

import logging
import struct
from typing import Dict, List, Optional, Tuple

from ..config import Committee
from ..crypto import Digest, PublicKey
from ..messages import Round
from ..primary.messages import Certificate, genesis
from .tusk import _check_scheme_trailer, _scheme_trailer

log = logging.getLogger("narwhal.consensus")

# dag: Round → {origin → (certificate digest, certificate)}
Dag = Dict[Round, Dict[PublicKey, Tuple[Digest, Certificate]]]


class GoldenState:
    """Consensus state (reference lib.rs:19-62) — dict-DAG only."""

    def __init__(self, genesis_certs: List[Certificate]) -> None:
        gen = {c.origin: (c.digest(), c) for c in genesis_certs}
        self.last_committed_round: Round = 0
        self.last_committed: Dict[PublicKey, Round] = {
            name: cert.round for name, (_, cert) in gen.items()
        }
        self.dag: Dag = {0: gen}

    _CKPT_MAGIC = b"NCKPT1"

    def snapshot_bytes(self) -> bytes:
        out = bytearray(self._CKPT_MAGIC)
        out += struct.pack("<Q", self.last_committed_round)
        items = sorted(self.last_committed.items())
        out += struct.pack("<I", len(items))
        for name, round in items:
            if len(bytes(name)) != 32:
                raise ValueError("checkpoint: authority key must be 32 bytes")
            out += bytes(name) + struct.pack("<Q", round)
        out += _scheme_trailer()
        return bytes(out)

    def restore(self, blob: bytes) -> None:
        if len(blob) < 18 or blob[:6] != self._CKPT_MAGIC:
            raise ValueError("checkpoint: bad magic")
        (last_round,) = struct.unpack_from("<Q", blob, 6)
        (n,) = struct.unpack_from("<I", blob, 14)
        _check_scheme_trailer(blob, 18 + 40 * n)
        entries = []
        pos = 18
        for _ in range(n):
            name = PublicKey(blob[pos : pos + 32])
            (round,) = struct.unpack_from("<Q", blob, pos + 32)
            entries.append((name, round))
            pos += 40
        self.last_committed_round = last_round
        for name, round in entries:
            self.last_committed[name] = round

    def update(self, certificate: Certificate, gc_depth: Round) -> None:
        """Record a commit and garbage-collect the DAG window — the
        per-certificate full-DAG sweep the indexed State batches away."""
        origin = certificate.origin
        self.last_committed[origin] = max(
            self.last_committed.get(origin, 0), certificate.round
        )
        self.last_committed_round = max(self.last_committed.values())
        last = self.last_committed_round
        for name, round in self.last_committed.items():
            for r in list(self.dag):
                authorities = self.dag[r]
                if name in authorities and r < round:
                    del authorities[name]
                if not authorities or r + gc_depth < last:
                    del self.dag[r]


class GoldenTusk:
    """The r06 commit rule: feed certificates, get ordered commit batches."""

    def __init__(
        self, committee: Committee, gc_depth: Round, fixed_coin: bool = False
    ) -> None:
        self.committee = committee
        self.gc_depth = gc_depth
        self.fixed_coin = fixed_coin
        self.state = GoldenState(genesis(committee))
        self._sorted_keys = sorted(committee.authorities.keys())

    def leader(self, round: Round, dag: Dag) -> Optional[Tuple[Digest, Certificate]]:
        coin = 0 if self.fixed_coin else round
        name = self._sorted_keys[coin % len(self._sorted_keys)]
        return dag.get(round, {}).get(name)

    def insert_certificate(self, certificate: Certificate) -> None:
        self.state.dag.setdefault(certificate.round, {})[
            certificate.origin
        ] = (certificate.digest(), certificate)

    def process_certificate(self, certificate: Certificate) -> List[Certificate]:
        state = self.state
        round = certificate.round
        self.insert_certificate(certificate)

        r = round - 1
        if r % 2 != 0 or r < 4:
            return []
        leader_round = r - 2
        if leader_round <= state.last_committed_round:
            return []
        got = self.leader(leader_round, state.dag)
        if got is None:
            return []
        leader_digest, leader = got

        # f+1 support, recomputed from scratch over all of round r-1.
        stake = sum(
            self.committee.stake(cert.origin)
            for _, cert in state.dag.get(r - 1, {}).values()
            if leader_digest in cert.header.parents
        )
        if stake < self.committee.validity_threshold():
            return []

        sequence: List[Certificate] = []
        for past_leader in reversed(self.order_leaders(leader)):
            for x in self.order_dag(past_leader):
                state.update(x, self.gc_depth)
                sequence.append(x)
        return sequence

    def order_leaders(self, leader: Certificate) -> List[Certificate]:
        to_commit = [leader]
        state = self.state
        for r in range(
            leader.round - 2, state.last_committed_round + 1, -2
        ):
            got = self.leader(r, state.dag)
            if got is None:
                continue
            _, prev_leader = got
            if self.linked(leader, prev_leader, state.dag):
                to_commit.append(prev_leader)
                leader = prev_leader
        return to_commit

    def linked(
        self, leader: Certificate, prev_leader: Certificate, dag: Dag
    ) -> bool:
        """Round-by-round BFS with per-hop list-membership checks."""
        parents = [leader]
        for r in range(leader.round - 1, prev_leader.round - 1, -1):
            parents = [
                certificate
                for digest, certificate in dag.get(r, {}).values()
                if any(digest in x.header.parents for x in parents)
            ]
        return any(x is prev_leader or x == prev_leader for x in parents)

    def order_dag(self, leader: Certificate) -> List[Certificate]:
        """DFS flatten with linear-scan parent resolution."""
        state = self.state
        ordered: List[Certificate] = []
        already_ordered = set()
        buffer = [leader]
        while buffer:
            x = buffer.pop()
            ordered.append(x)
            for parent in sorted(x.header.parents):
                found = None
                for digest, certificate in state.dag.get(x.round - 1, {}).values():
                    if digest == parent:
                        found = (digest, certificate)
                        break
                if found is None:
                    continue  # already ordered or GC'd up to here
                digest, certificate = found
                skip = digest in already_ordered
                skip |= (
                    state.last_committed.get(certificate.origin, -1)
                    >= certificate.round
                )
                if not skip:
                    buffer.append(certificate)
                    already_ordered.add(digest)
        ordered = [
            x
            for x in ordered
            if x.round + self.gc_depth >= state.last_committed_round
        ]
        ordered.sort(key=lambda x: x.round)  # stable: prettier sequence
        return ordered
