"""In-memory transport: the network plane of the simulation harness.

Installed behind ``narwhal_tpu/network/transport.py`` (the seam every
``Receiver.spawn`` / ``SimpleSender()`` / ``ReliableSender()`` /
BatchMaker client-socket bind consults), so a whole committee's traffic
— primaries, workers, clients — routes through seeded in-process queues
on ONE event loop.  Semantics mirror the TCP classes and the
``faults/netem.py`` emulator they normally compose with:

- **per-pair shaping** re-uses the netem ``Shape`` (latency + jitter +
  loss) and partition-window vocabulary, compiled by
  :func:`compile_wan` from the same ``WanSpec`` the socketed
  fault_bench compiles — but a shaped delay becomes a virtual-time
  ``call_later``, never a real sleep, so a 120 ms WAN RTT costs
  microseconds of wall time under the virtual clock;
- **loss and partitions surface as the real recovery paths**: the
  reliable channel counts a retransmission and re-offers after the
  jittered exponential backoff (``next_backoff`` — the exact reconnect
  schedule of the TCP sender), the simple channel drops visibly, and an
  unreachable peer ticks the same per-peer failure gauges the
  ``peer_unreachable`` health rule consumes, with the same
  never-connected boot grace;
- **ordering** matches TCP: frames of one (sender, destination) channel
  deliver in send order (jitter never reorders within a channel), and
  each receiver processes one channel's frames sequentially while
  channels proceed independently (the per-connection task of the real
  Receiver).

Every stochastic draw comes from a ``random.Random`` seeded from the
scenario seed and the (src, dst) pair, so the same (seed, spec) replays
byte-for-byte.
"""

from __future__ import annotations

import asyncio
import collections
import contextvars
import random
import zlib
from typing import Deque, Dict, List, Optional, Tuple

from .. import metrics
from ..faults.netem import Shape, resolve_wan_plane
from ..network.clocksync import parse_ack, record_ack_sample
from ..network.framing import MAX_FRAME, parse_address
from ..network.reliable_sender import (
    _BACKOFF_START,
    _NEVER_CONNECTED_GRACE_S,
    _peer_instruments,
    next_backoff,
)
from ..utils.clock import current_skew, wall_now
from ..utils.tasks import spawn

_m_frames = metrics.counter("net.sim.frames_delivered")
_m_bytes = metrics.counter("net.sim.bytes_delivered")
_m_dropped = metrics.counter("net.sim.dropped")
_m_lost = metrics.counter("net.sim.emulated_losses")
_m_retrans = metrics.counter("net.sim.retransmissions")


def compile_wan(scenario, committee, names) -> Dict[str, dict]:
    """The shared scenario wan-plane resolution
    (``faults/netem.py::resolve_wan_plane`` — one compilation for both
    the socketed and simulated harnesses), with partition peer lists
    turned into sets for this transport's per-frame membership checks."""
    table = resolve_wan_plane(scenario, committee, names)
    for entry in table.values():
        for part in entry["partitions"]:
            part["peers"] = set(part["peers"])
    return table


class SimTransport:
    """One committee's in-memory network (install via
    ``narwhal_tpu.network.transport.install``).

    ``wan_table`` is :func:`compile_wan` output; ``backoff_cap_s`` is the
    scenario's reconnect-backoff ceiling (the NARWHAL_NET_BACKOFF_MAX_S
    knob, injected instead of read from the environment so in-process
    runs never mutate ``os.environ``)."""

    def __init__(
        self,
        seed: int,
        wan_table: Optional[Dict[str, dict]] = None,
        backoff_cap_s: float = 60.0,
    ) -> None:
        self.seed = seed
        self.wan = wan_table or {}
        self.backoff_cap_s = max(_BACKOFF_START, float(backoff_cap_s))
        self.listeners: Dict[str, "_SimReceiver"] = {}
        self.tx_servers: Dict[str, "_SimTxServer"] = {}
        self.down: set = set()  # addresses of crashed authorities
        self.start_time: Optional[float] = None  # virtual anchor
        self._booting = ""  # label of the node being spawned
        self._serial = 0  # per-sender seed discriminator

    # -- harness hooks --------------------------------------------------------

    def anchor(self, now: float) -> None:
        """Anchor the partition-window clock (virtual launch instant)."""
        self.start_time = now

    class _NodeScope:
        def __init__(self, tr: "SimTransport", label: str) -> None:
            self.tr, self.label = tr, label

        def __enter__(self):
            self._prev = self.tr._booting
            self.tr._booting = self.label
            return self.tr

        def __exit__(self, *exc):
            self.tr._booting = self._prev

    def node(self, label: str) -> "_NodeScope":
        """Scope sender construction to ``label`` — every sender built
        inside carries that source identity for per-pair shaping."""
        return self._NodeScope(self, label)

    def set_down(self, addresses) -> None:
        """Crash: the addresses stop accepting AND established channels
        start failing (SIGKILL analog; listeners are dropped by the
        node's own shutdown)."""
        self.down.update(addresses)

    def set_up(self, addresses) -> None:
        self.down.difference_update(addresses)

    # -- seam surface (network/transport.py contract) -------------------------

    def spawn_receiver(self, address: str, handler, classify=None):
        receiver = _SimReceiver(self, address, handler, classify)
        self.listeners[address] = receiver
        return receiver

    def simple_sender(self) -> "_SimSimpleSender":
        self._serial += 1
        return _SimSimpleSender(self, self._booting, self._serial)

    def reliable_sender(self) -> "_SimReliableSender":
        self._serial += 1
        return _SimReliableSender(self, self._booting, self._serial)

    def create_tx_server(self, address: str, protocol_factory):
        server = _SimTxServer(self, address, protocol_factory)
        self.tx_servers[address] = server
        return server

    def open_tx_connection(self, address: str) -> "_SimTxConnection":
        """Harness-side client ingress: a connection into the worker's
        transaction plane (raises like a refused connect when the
        address is down or unbound)."""
        server = self.tx_servers.get(address)
        if server is None or address in self.down:
            raise OSError(f"sim: no tx listener on {address}")
        return server.connect()

    # -- shaping --------------------------------------------------------------

    def pair_rng(self, src: str, dst: str, serial: int) -> random.Random:
        return random.Random(
            self.seed
            ^ zlib.crc32(src.encode())
            ^ (zlib.crc32(dst.encode()) << 1)
            ^ (serial << 17)
        )

    def shape_for(self, src: str, dst: str) -> Optional[Shape]:
        entry = self.wan.get(src)
        if not entry:
            return None
        fallback = None
        for r in entry["rules"]:
            d = r.get("dst", "*")
            if d == dst:
                return Shape(
                    latency_ms=float(r.get("latency_ms", 0.0)),
                    jitter_ms=float(r.get("jitter_ms", 0.0)),
                    loss=float(r.get("loss", 0.0)),
                )
            if d == "*":
                fallback = r
        if fallback is not None:
            return Shape(
                latency_ms=float(fallback.get("latency_ms", 0.0)),
                jitter_ms=float(fallback.get("jitter_ms", 0.0)),
                loss=float(fallback.get("loss", 0.0)),
            )
        return None

    def partitioned(self, src: str, dst: str, now: float) -> bool:
        entry = self.wan.get(src)
        if not entry or self.start_time is None:
            return False
        t = now - self.start_time
        for w in entry["partitions"]:
            if dst in w["peers"] and t >= w["from_s"] and (
                w["until_s"] is None or t < w["until_s"]
            ):
                return True
        return False

    def unreachable(self, src: str, dst: str, now: float) -> bool:
        """Connect-time failure: dead/crashed/unbound peer or an open
        partition window — the shapes a TCP connect() would refuse."""
        return (
            dst in self.down
            or dst not in self.listeners
            or self.partitioned(src, dst, now)
        )

    def arrive(
        self,
        dst: str,
        chan_key: Tuple,
        data: bytes,
        msg_type: str,
        reply_cb,
    ) -> None:
        """Hand one frame to its listener NOW.  The listener is resolved
        at arrival time: a frame in flight when its destination crashes
        is lost with the crash."""
        listener = self.listeners.get(dst)
        if listener is None or dst in self.down:
            _m_dropped.inc()
            return
        _m_frames.inc()
        _m_bytes.inc(len(data))
        listener.enqueue(chan_key, data, msg_type, reply_cb)

    def schedule(self, due: float, fire) -> None:
        """Run ``fire`` at virtual ``due``, quantized to a 1 ms arrival
        grid: per-pair jitter draws otherwise give every frame its own
        due instant, and every distinct instant costs one full loop tick
        (clock jump + selector poll) — the measured #1 cost of a shaped
        N=20 run.  Callers that need ordering keep their own FIFO and
        let ``fire`` release the queue HEAD, so arrival order within a
        channel never depends on timer tie-breaking.  Zero-delay fires
        run synchronously: callers are sender tasks, already decoupled
        from dispatch by the receiver's channel queue."""
        loop = asyncio.get_running_loop()
        due = -(-due * 1000 // 1) / 1000
        delay = due - loop.time()
        if delay <= 0:
            fire()
        else:
            loop.call_later(delay, fire)

    async def shutdown(self) -> None:
        """Tear down every channel/listener task (end of run)."""
        for server in list(self.tx_servers.values()):
            server.close()
        for receiver in list(self.listeners.values()):
            await receiver.shutdown()
        self.listeners.clear()
        self.tx_servers.clear()


# -- receiver -----------------------------------------------------------------


class _SimWriter:
    """Reply channel handed to handlers: first reply resolves the
    sender-side delivery future (the ACK payload); extra replies are
    drained-and-discarded like the TCP senders do."""

    __slots__ = ("_reply",)

    def __init__(self, reply_cb) -> None:
        self._reply = reply_cb

    async def send(self, data: bytes) -> None:
        cb, self._reply = self._reply, None
        if cb is not None:
            cb(data)


class _SimReceiver:
    """Address-bound listener: one dispatch task per channel (the
    per-connection task of the real Receiver), frames processed in
    delivery order within a channel."""

    def __init__(self, transport, address, handler, classify) -> None:
        self.transport = transport
        self.address = address
        self.handler = handler
        self.classify = classify
        self._channels: Dict[Tuple, Tuple[Deque, asyncio.Event, asyncio.Task]] = {}
        self._closed = False
        # The receiver is constructed inside its node's boot scope, but
        # channel tasks are spawned lazily from the SENDER's context
        # (enqueue fires in the sending channel's task or a timer).
        # Capture the boot context so handlers — which stamp ACKs with
        # wall_now() — run under THIS node's injected clock skew, not
        # whichever sender happened to deliver the first frame.
        self._ctx = contextvars.copy_context()

    @property
    def port(self) -> int:
        return parse_address(self.address)[1]

    def enqueue(self, chan_key, data, msg_type, reply_cb) -> None:
        if self._closed:
            _m_dropped.inc()
            return
        chan = self._channels.get(chan_key)
        if chan is None:
            q: Deque = collections.deque()
            ev = asyncio.Event()
            task = self._ctx.run(
                spawn, self._chan_loop(q, ev), name="sim-recv-chan"
            )
            chan = self._channels[chan_key] = (q, ev, task)
        q, ev, _ = chan
        q.append((data, msg_type, reply_cb))
        ev.set()

    async def _chan_loop(self, q: Deque, ev: asyncio.Event) -> None:
        while True:
            while not q:
                ev.clear()
                await ev.wait()
            data, msg_type, reply_cb = q.popleft()
            metrics.wire_account(
                "in",
                self.classify(data) if self.classify else "unframed",
                "sim",
                len(data),
            )
            try:
                await self.handler.dispatch(_SimWriter(reply_cb), data)
            except asyncio.CancelledError:
                raise
            except Exception:
                import logging

                logging.getLogger("narwhal.sim").exception(
                    "Handler error on %s", self.address
                )

    async def shutdown(self) -> None:
        self._closed = True
        self.transport.listeners.pop(self.address, None)
        tasks = [t for (_, _, t) in self._channels.values()]
        for t in tasks:
            t.cancel()
        if tasks:
            await asyncio.gather(*tasks, return_exceptions=True)
        self._channels.clear()


# -- senders ------------------------------------------------------------------


class _SimMsg:
    __slots__ = ("data", "fut", "msg_type", "accounted")

    def __init__(self, data, fut, msg_type) -> None:
        self.data = data
        self.fut = fut
        self.msg_type = msg_type
        self.accounted = False


class _SimRelChannel:
    """One reliable (src → dst) channel: queued messages survive
    unreachability and loss through the real jittered-exponential
    backoff schedule, per-peer health instruments tick exactly like the
    TCP sender's, and each delivery future resolves with the peer's ACK
    payload."""

    def __init__(self, transport, src: str, dst: str, serial: int) -> None:
        self.transport = transport
        self.src = src
        self.dst = dst
        self.queue: Deque[_SimMsg] = collections.deque()
        self.wakeup = asyncio.Event()
        self.rng = transport.pair_rng(src, dst, serial)
        self.delay = _BACKOFF_START
        self.backing_off = False
        self.failures = 0
        self.ever_connected = False
        self.last_due = 0.0
        # Frames "on the wire": released strictly FIFO by the timers
        # schedule() arms (each fire pops the head, so channel order is
        # independent of timer tie-breaking on the quantized grid).
        self._inflight: Deque = collections.deque()
        loop = asyncio.get_running_loop()
        self.created = loop.time()
        # The channel is created from the sending node's task context;
        # remember its skew so the ACK-receive stamp can be re-expressed
        # on the SENDER's clock (the _acked callback runs in the
        # receiver's channel-loop context).
        self.src_skew = current_skew()
        (
            self._m_rtt,
            self._m_peer_retrans,
            self._g_failures,
            self._g_backoff,
        ) = _peer_instruments(dst)
        self.task = spawn(self._run(), name="sim-reliable-chan")

    def push(self, msg: _SimMsg) -> None:
        self.queue.append(msg)
        self.wakeup.set()

    async def _run(self) -> None:
        transport = self.transport
        loop = asyncio.get_running_loop()
        shape = transport.shape_for(self.src, self.dst)
        while True:
            while not self.queue:
                self.wakeup.clear()
                await self.wakeup.wait()
            msg = self.queue[0]
            if msg.fut.cancelled():
                self.queue.popleft()
                continue
            now = loop.time()
            if transport.unreachable(self.src, self.dst, now):
                # Same failure accounting as _Connection._keep_alive,
                # including the never-connected boot grace.
                self.backing_off = True
                self.failures += 1
                if self.ever_connected or (
                    now - self.created > _NEVER_CONNECTED_GRACE_S
                ):
                    self._g_failures.set(self.failures)
                self._g_backoff.set(1)
                sleep_s, self.delay = next_backoff(
                    self.delay, cap=transport.backoff_cap_s, rng=self.rng
                )
                await asyncio.sleep(sleep_s)
                continue
            if self.backing_off or not self.ever_connected:
                self.delay = _BACKOFF_START
                self.backing_off = False
                self.ever_connected = True
                self.failures = 0
                self._g_failures.set(0)
                self._g_backoff.set(0)
            if shape is not None and shape.loss and (
                self.rng.random() < shape.loss
            ):
                # TCP loses segments, not messages: the frame will be
                # written again after a backoff window — a counted
                # retransmission, the signal a lossy link leaves.
                _m_lost.inc()
                _m_retrans.inc()
                self._m_peer_retrans.inc()
                retrans_wait, _ = next_backoff(
                    _BACKOFF_START, cap=transport.backoff_cap_s, rng=self.rng
                )
                metrics.wire_account(
                    "out", msg.msg_type, self.dst, len(msg.data),
                    retransmit=msg.accounted,
                )
                msg.accounted = True
                await asyncio.sleep(retrans_wait)
                continue
            self.queue.popleft()
            delay_s = shape.delay_s(self.rng) if shape is not None else 0.0
            due = max(now + delay_s, self.last_due)
            self.last_due = due
            t0 = now
            t0_wall = wall_now()  # sender context: carries src skew
            fut = msg.fut

            def _acked(
                payload: bytes, fut=fut, t0=t0, t0_wall=t0_wall
            ) -> None:
                self._m_rtt.observe(loop.time() - t0)
                # Same piggyback offset sampling as the TCP read_loop,
                # with the receive stamp mapped back onto the sender's
                # clock (this callback fires in the receiver's context).
                t_peer = parse_ack(payload)
                if t_peer is not None:
                    t_recv = wall_now() - current_skew() + self.src_skew
                    record_ack_sample(
                        self.dst, t0_wall, t_recv, t_peer, src=self.src
                    )
                if not fut.done():
                    fut.set_result(payload)

            metrics.wire_account(
                "out", msg.msg_type, self.dst, len(msg.data),
                retransmit=msg.accounted,
            )
            msg.accounted = True
            self._inflight.append((msg.data, msg.msg_type, _acked))
            transport.schedule(due, self._release)

    def _release(self) -> None:
        data, msg_type, acked = self._inflight.popleft()
        self.transport.arrive(self.dst, (id(self),), data, msg_type, acked)

    def abort_all(self) -> None:
        for msg in self.queue:
            if not msg.fut.done():
                msg.fut.cancel()
        self.queue.clear()


class _SimReliableSender:
    """Drop-in ReliableSender: ``send`` returns a future resolved with
    the peer's ACK payload; cancel abandons delivery."""

    def __init__(self, transport, src: str, serial: int) -> None:
        self.transport = transport
        self.src = src
        self._serial = serial
        self._channels: Dict[str, _SimRelChannel] = {}
        # Lucky-broadcast sampling draws from a seeded per-sender stream
        # (not the module RNG) so peer selection replays bit-identically
        # per (seed, spec).
        self._lucky_rng = transport.pair_rng(src, "lucky", serial)

    def _channel(self, address: str) -> _SimRelChannel:
        chan = self._channels.get(address)
        if chan is None or chan.task.done():
            chan = self._channels[address] = _SimRelChannel(
                self.transport, self.src, address, self._serial
            )
        return chan

    def send(
        self, address: str, data: bytes, msg_type: str = "other"
    ) -> asyncio.Future:
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        if len(data) > MAX_FRAME:
            fut.set_exception(
                ValueError(f"message of {len(data)} bytes exceeds MAX_FRAME")
            )
            return fut
        self._channel(address).push(_SimMsg(data, fut, msg_type))
        return fut

    def broadcast(
        self, addresses, data: bytes, msg_type: str = "other"
    ) -> List[asyncio.Future]:
        return [self.send(addr, data, msg_type) for addr in addresses]

    def lucky_broadcast(
        self, addresses, data: bytes, nodes: int, msg_type: str = "other"
    ) -> List[asyncio.Future]:
        from ..network.framing import sample_peers

        return self.broadcast(
            sample_peers(addresses, nodes, rng=self._lucky_rng),
            data, msg_type,
        )

    def close(self) -> None:
        for chan in self._channels.values():
            chan.task.cancel()
            chan.abort_all()
        self._channels.clear()


class _SimSimpleSender:
    """Drop-in SimpleSender: best-effort, partitioned/lost frames are
    visible drops."""

    def __init__(self, transport, src: str, serial: int) -> None:
        self.transport = transport
        self.src = src
        self._serial = serial
        self._lucky_rng = transport.pair_rng(src, "lucky", serial)
        self._rngs: Dict[str, random.Random] = {}
        self._last_due: Dict[str, float] = {}
        self._inflight: Dict[str, Deque] = {}
        # shape_for is a linear rule scan; memoize per destination like
        # the reliable channel does (helper/sync re-serves ride this
        # sender, thousands of frames per shaped run).
        self._shapes: Dict[str, Optional[Shape]] = {}

    def send(
        self, address: str, data: bytes, msg_type: str = "other"
    ) -> None:
        transport = self.transport
        loop = asyncio.get_running_loop()
        now = loop.time()
        if transport.unreachable(self.src, address, now):
            _m_dropped.inc()
            return
        rng = self._rngs.get(address)
        if rng is None:
            rng = self._rngs[address] = transport.pair_rng(
                self.src, address, self._serial
            )
        if address in self._shapes:
            shape = self._shapes[address]
        else:
            shape = self._shapes[address] = transport.shape_for(
                self.src, address
            )
        if shape is not None and shape.loss and rng.random() < shape.loss:
            _m_lost.inc()
            _m_dropped.inc()
            return
        delay_s = shape.delay_s(rng) if shape is not None else 0.0
        due = max(now + delay_s, self._last_due.get(address, 0.0))
        self._last_due[address] = due
        metrics.wire_account("out", msg_type, address, len(data))
        inflight = self._inflight.get(address)
        if inflight is None:
            inflight = self._inflight[address] = collections.deque()
        inflight.append((data, msg_type))
        transport.schedule(
            due, lambda addr=address: self._release(addr)
        )

    def _release(self, address: str) -> None:
        data, msg_type = self._inflight[address].popleft()
        self.transport.arrive(
            address, (id(self), address), data, msg_type,
            lambda _payload: None,
        )

    def broadcast(self, addresses, data: bytes, msg_type: str = "other") -> None:
        for addr in addresses:
            self.send(addr, data, msg_type)

    def lucky_broadcast(
        self, addresses, data: bytes, nodes: int, msg_type: str = "other"
    ) -> None:
        from ..network.framing import sample_peers

        self.broadcast(
            sample_peers(addresses, nodes, rng=self._lucky_rng),
            data, msg_type,
        )

    def close(self) -> None:
        self._rngs.clear()
        self._last_due.clear()


# -- client-transaction ingress ----------------------------------------------


class _SimTxTransport:
    """Transport stand-in handed to _TxProtocol.connection_made."""

    __slots__ = ("closed", "paused")

    def __init__(self) -> None:
        self.closed = False
        self.paused = False

    def pause_reading(self) -> None:
        self.paused = True

    def resume_reading(self) -> None:
        self.paused = False

    def close(self) -> None:
        self.closed = True


class _SimTxConnection:
    """One in-memory client connection: ``write`` feeds raw stream bytes
    to the worker's tx protocol on the next loop tick (decoupled like a
    socket's data_received)."""

    def __init__(self, protocol) -> None:
        self.protocol = protocol
        self.transport = _SimTxTransport()
        protocol.connection_made(self.transport)

    def write(self, data: bytes) -> None:
        if self.transport.closed:
            return
        asyncio.get_running_loop().call_soon(self._feed, bytes(data))

    def _feed(self, data: bytes) -> None:
        if not self.transport.closed:
            self.protocol.data_received(data)

    def close(self) -> None:
        if not self.transport.closed:
            self.transport.closed = True
            self.protocol.connection_lost(None)


class _SimTxServer:
    """The BatchMaker-facing bind object (close() + a sockets attr for
    API compatibility)."""

    sockets: tuple = ()

    def __init__(self, transport, address, protocol_factory) -> None:
        self.transport = transport
        self.address = address
        self.protocol_factory = protocol_factory
        self.closed = False

    def connect(self) -> _SimTxConnection:
        if self.closed:
            raise OSError(f"sim: tx listener on {self.address} is closed")
        return _SimTxConnection(self.protocol_factory())

    def close(self) -> None:
        self.closed = True
        self.transport.tx_servers.pop(self.address, None)
