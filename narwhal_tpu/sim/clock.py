"""The virtual clock: simulated time over the exploring event loop.

:class:`VirtualClockLoop` subclasses
:class:`~narwhal_tpu.analysis.schedule.ExploringEventLoop` (so every run
keeps the seeded same-tick schedule exploration) and replaces the loop's
clock with a simulated one:

- ``time()`` returns the virtual now — every ``loop.time()`` deadline,
  ``call_later`` timer, ``asyncio.sleep`` and ``wait_for`` in the
  process rides it, as do the protocol's retry/age computations since
  they read :func:`narwhal_tpu.utils.clock.loop_now`;
- at the top of every tick, if NO callback is ready and at least one
  timer is scheduled, the clock JUMPS to the earliest timer's deadline
  instead of letting the selector sleep — quiesce costs microseconds of
  wall time, whatever the virtual gap.  While anything is runnable the
  clock holds still, so CPU-bound protocol work executes exactly as it
  would under a schedule where the host is infinitely fast (the
  FoundationDB simulation contract: virtual time advances only at
  quiesce points).

Two safety knobs (declared in the typed env registry):

- ``NARWHAL_SIM_COMPRESSION_CAP`` — ceiling on a single quiesce jump in
  virtual seconds.  A forgotten far-future timer then advances the clock
  in bounded, *non-blocking* steps (the loop re-arms itself with a
  no-op callback) instead of swallowing the whole scenario in one leap.
- ``NARWHAL_SIM_MAX_VIRTUAL_S`` — ceiling on a run's total virtual
  duration, enforced by :func:`run_virtual` as a virtual-time
  ``wait_for`` so a livelocked scenario terminates with a diagnosable
  timeout instead of spinning forever.

Determinism: the jump rule is a pure function of the loop's own timer
heap, the no-op re-arm callbacks are plain-function handles the
explorer never permutes, and nothing here reads the wall clock except
the run stats — same seed, same workload → same tick sequence, same
virtual timestamps, byte-identical outcomes.
"""

from __future__ import annotations

import heapq
import time as _wall
from typing import Any, Callable, Coroutine, Optional

from ..analysis.schedule import ExploringEventLoop, _cancel_pending
from ..utils.env import env_float

__all__ = ["VirtualClockLoop", "run_virtual"]

_JUMP_CAP_DEFAULT = 60.0


def _noop() -> None:
    """Re-arm callback for capped jumps: keeps the selector non-blocking
    so the next tick can continue advancing the clock."""


class _ThriftySelector:
    """Selector wrapper that elides most zero-timeout polls.

    A simulated committee's loop runs tens of thousands of ticks whose
    selector poll can never return anything (all I/O is in-memory), yet
    each ``select(0)`` is a real ``epoll_wait`` syscall — on sandboxed
    hosts with intercepted syscalls (~50 µs each here) that was the #1
    cost of a shaped N=20 run.  Zero-timeout polls are answered with an
    empty event list except every 64th (so the self-pipe — the only
    registered fd, carrying cross-thread wakeups — is still drained
    regularly); blocking polls (timeout None/positive) always hit the
    real selector, so genuine waits keep their semantics.  The skip
    counter is deterministic: same workload, same polls skipped."""

    __slots__ = ("_inner", "_zeros")

    def __init__(self, inner) -> None:
        self._inner = inner
        self._zeros = 0

    def select(self, timeout=None):
        if timeout == 0:
            self._zeros += 1
            if self._zeros % 64:
                return []
        return self._inner.select(timeout)

    def __getattr__(self, name):
        return getattr(self._inner, name)


class VirtualClockLoop(ExploringEventLoop):
    """Exploring event loop on simulated time (see module docstring).

    ``jumps`` counts quiesce advances, ``virtual_elapsed()`` the total
    simulated seconds — together with the harness's wall measurement
    they are the compression-ratio witness the sim artifact reports.
    """

    def __init__(
        self,
        seed: int,
        start: float = 0.0,
        max_jump_s: Optional[float] = None,
    ) -> None:
        super().__init__(seed)
        self._sim_now = float(start)
        self._sim_start = float(start)
        self._max_jump = (
            float(env_float("NARWHAL_SIM_COMPRESSION_CAP", _JUMP_CAP_DEFAULT))
            if max_jump_s is None
            else float(max_jump_s)
        )
        self.jumps = 0
        self.capped_jumps = 0
        self._selector = _ThriftySelector(self._selector)

    def time(self) -> float:  # noqa: D401 (asyncio clock hook)
        return self._sim_now

    def virtual_elapsed(self) -> float:
        return self._sim_now - self._sim_start

    def _run_once(self) -> None:
        if not self._ready and not self._stopping and self._scheduled:
            # Drop cancelled heads first: a dead timer must not absorb
            # the jump (the base loop would pop it immediately anyway,
            # but only AFTER computing a select timeout from it).
            while self._scheduled and self._scheduled[0]._cancelled:
                handle = heapq.heappop(self._scheduled)
                handle._scheduled = False
                self._timer_cancelled_count -= 1
            if self._scheduled:
                when = self._scheduled[0]._when
                gap = when - self._sim_now
                if gap > 0:
                    if 0 < self._max_jump < gap:
                        self._sim_now += self._max_jump
                        self.capped_jumps += 1
                        # Keep select(timeout) at zero: with nothing
                        # ready and the head timer still in the future,
                        # the base loop would otherwise sleep the
                        # REMAINING gap in wall time.
                        self.call_soon(_noop)
                    else:
                        self._sim_now = when
                    self.jumps += 1
        super()._run_once()


def run_virtual(
    main: Callable[[], Coroutine],
    seed: int,
    max_virtual_s: Optional[float] = None,
    start: float = 0.0,
    wall_timeout_s: float = 600.0,
) -> Any:
    """``asyncio.run`` under a :class:`VirtualClockLoop`; returns
    ``(result, stats)`` where ``stats`` carries the schedule counters of
    :func:`~narwhal_tpu.analysis.schedule.run_with_seed` plus the
    virtual/wall split (``virtual_s``, ``wall_s``, ``compression``,
    ``jumps``).

    ``max_virtual_s`` (default ``NARWHAL_SIM_MAX_VIRTUAL_S``) bounds the
    run in VIRTUAL seconds via ``wait_for`` — on the virtual clock a
    deadlocked or livelocked scenario reaches the bound near-instantly
    in wall terms, so the guard is deterministic: the same seed always
    times out at the same virtual instant with the same state.

    ``wall_timeout_s`` is the last-resort backstop the virtual guard
    cannot provide: a BUSY livelock (a task that never quiesces, e.g. a
    ``sleep(0)`` spin) keeps the clock from ever advancing, so the
    virtual deadline never becomes due — after this many WALL seconds a
    timer thread cancels the run (surfaced as CancelledError), turning
    an indefinite hang into a failure with the seed attached.  It is
    deliberately far above any legitimate run and only nondeterministic
    on runs that would otherwise never finish.  0 disables it."""
    import asyncio
    import threading

    if max_virtual_s is None:
        max_virtual_s = float(env_float("NARWHAL_SIM_MAX_VIRTUAL_S", 600.0))
    loop = VirtualClockLoop(seed, start=start)
    wall0 = _wall.perf_counter()

    # Running-loop lookup pin.  Every get_running_loop() does a C-level
    # getpid() (fork protection) — a real syscall that sandboxed hosts
    # (gVisor-style interception; this container measures ~20 µs per
    # getpid) turn into the single largest per-message cost of a
    # simulated committee: queues, sleeps, futures and the protocol's
    # own call sites all route through it, six-figure call counts per
    # run.  Inside run_virtual exactly ONE loop can ever be running, so
    # the lookup is pinned to it for the duration and restored after.
    import asyncio.events as _events

    def _pinned_get_running_loop() -> "asyncio.AbstractEventLoop":
        # _thread_id is BaseEventLoop's own "am I running" marker — an
        # attribute read, not a syscall.
        if loop._thread_id is not None:
            return loop
        raise RuntimeError("no running event loop")

    def _pinned_peek_running_loop():
        return loop if loop._thread_id is not None else None

    saved = (
        asyncio.get_running_loop,
        _events.get_running_loop,
        _events._get_running_loop,
    )
    try:
        asyncio.get_running_loop = _pinned_get_running_loop  # type: ignore
        _events.get_running_loop = _pinned_get_running_loop  # type: ignore
        _events._get_running_loop = _pinned_peek_running_loop  # type: ignore
        asyncio.set_event_loop(loop)
        coro = main()
        if max_virtual_s and max_virtual_s > 0:
            coro = asyncio.wait_for(coro, max_virtual_s)
        task = loop.create_task(coro)
        backstop: Optional[threading.Timer] = None
        if wall_timeout_s and wall_timeout_s > 0:
            backstop = threading.Timer(
                wall_timeout_s,
                # call_soon_threadsafe lands in the ready queue even
                # mid-spin, so the cancel reaches a busy livelock too.
                lambda: loop.call_soon_threadsafe(task.cancel),
            )
            backstop.daemon = True
            backstop.start()
        try:
            result = loop.run_until_complete(task)
        finally:
            if backstop is not None:
                backstop.cancel()
        wall_s = _wall.perf_counter() - wall0
        virtual_s = loop.virtual_elapsed()
        return result, {
            "seed": seed,
            "ticks": loop.ticks,
            "permutations": loop.permutations,
            "jumps": loop.jumps,
            "capped_jumps": loop.capped_jumps,
            "virtual_s": round(virtual_s, 6),
            "wall_s": round(wall_s, 6),
            "compression": (
                round(virtual_s / wall_s, 2) if wall_s > 0 else None
            ),
        }
    finally:
        try:
            _cancel_pending(loop)
            loop.run_until_complete(loop.shutdown_asyncgens())
            # Same rationale as schedule.run_with_seed: join the default
            # executor so no thread survives into the next seeded run.
            loop.run_until_complete(loop.shutdown_default_executor())
        finally:
            (
                asyncio.get_running_loop,
                _events.get_running_loop,
                _events._get_running_loop,
            ) = saved  # type: ignore
            asyncio.set_event_loop(None)
            loop.close()
