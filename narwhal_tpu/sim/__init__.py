"""narwhal-sim: deterministic committee-at-scale simulation.

ROADMAP item 6 (FoundationDB-style simulation testing), built by fusing
three existing subsystems:

- the **virtual clock** (:mod:`.clock`): an
  :class:`~narwhal_tpu.analysis.schedule.ExploringEventLoop` subclass
  whose ``time()`` runs on simulated seconds — when every task quiesces,
  the clock JUMPS to the next timer instead of sleeping, so a 60-second
  scenario executes in well under a second of wall time while every
  retry window, health-rule rate and netem delay keeps its declared
  semantics;
- the **in-memory transport** (:mod:`.transport`): drop-in
  Receiver/SimpleSender/ReliableSender counterparts behind the
  ``network/transport.py`` seam, routing frames through seeded
  in-process queues with ``faults/netem.py``-semantics per-pair
  latency/jitter/loss/partitions compiled into virtual-time
  ``call_later`` delays;
- the **committee builder + judge** (:mod:`.committee`): boots every
  primary and worker of an N=4..50 committee on ONE exploring loop with
  in-memory stores, drives a fault scenario (byzantine plans, WAN
  shaping, crash/restart) through it, and judges the run with the
  existing three-verdict engine — golden-oracle audit replay
  (``consensus/replay.py``, the arXiv:2407.02167 invariants),
  payload-commit liveness in virtual time, and health-rule detection.

``benchmark/sim_bench.py`` sweeps (seed × fuzzed fault spec × committee
size) through :func:`run_sim_scenario` — thousands of explored points
per CI run, every divergence dumped as a replayable ``(seed, spec)``
repro file.
"""

from .clock import VirtualClockLoop, run_virtual
from .committee import run_sim_scenario
from .transport import SimTransport

__all__ = [
    "VirtualClockLoop",
    "run_virtual",
    "run_sim_scenario",
    "SimTransport",
]
