"""Committee-at-scale simulation: boot, drive, and judge one scenario.

:func:`run_sim_scenario` takes the same declarative
:class:`~narwhal_tpu.faults.spec.FaultScenario` the socketed
``benchmark/fault_bench.py`` runs, but executes the WHOLE committee —
every primary, worker and client of an N=4..50 validator set, plus its
Byzantine plans, WAN shaping and crash/restart timeline — as a single
process on one :class:`~narwhal_tpu.sim.clock.VirtualClockLoop`, with
the in-memory transport installed behind the ``network/`` seam and
in-memory stores throughout.  A 60-virtual-second scenario completes in
wall seconds; the run seed pins both the schedule exploration and every
stochastic draw, so the same ``(seed, spec)`` replays byte-for-byte.

The judge is the existing three-verdict engine:

- **safety** — per-node audit segments replayed through the frozen
  golden oracle (``consensus/replay.py``, the arXiv:2407.02167
  invariants) plus committee-wide commit-prefix consistency;
- **liveness** — honest survivors keep committing client payload after
  the fault settles, measured on the VIRTUAL clock;
- **detection** — every expected health rule FIRES into a
  :class:`~narwhal_tpu.metrics.HealthMonitor` driven on virtual time
  (and a clean scenario fires nothing).

Fidelity notes (documented, deliberate): all nodes share one process
registry, so detection is committee-aggregated (a rule firing anywhere
counts — per-node attribution belongs to the socketed harness); a sim
"crash" is an abrupt task teardown rather than a SIGKILL (the retained
in-memory store preserves exactly what the on-disk store preserves, but
torn-file recovery itself stays the socketed suite's subject); and
signatures run in sim-MAC mode (``crypto/keys.py``) — key-binding
semantics preserved, ed25519 math elided.
"""

from __future__ import annotations

import gc
import hashlib
import json
import random
from typing import Dict, List, Optional

from .. import metrics
from ..config import (
    Authority,
    Committee,
    Parameters,
    PrimaryAddresses,
    WorkerAddresses,
)
from ..consensus.replay import cross_node_prefix, replay_segments
from ..crypto import KeyPair
from ..crypto.keys import set_sim_mac
from ..faults.byzantine import ByzantinePlan
from ..faults.spec import FaultScenario
from ..metrics import HealthMonitor, default_rules
from ..network import clocksync
from ..network import transport as net_seam
from ..network.framing import frame
from ..utils.clock import set_wall_base, skew_scope
from ..utils.tasks import spawn
from .clock import run_virtual
from .transport import SimTransport, compile_wan

# Virtual-time settle margins (fault_bench's wall margins exist to absorb
# host scheduling noise; virtual time has none, only protocol cadence).
_RESTART_SETTLE_S = 6.0
_HEAL_SETTLE_S = 3.0
# Committee-wide client rate ceiling: the sim's subject is schedule/fault
# diversity, not ingress throughput, and wall cost is linear in frames.
_RATE_CAP = 600

# Which evidence counter backs each counter-latched detection rule (the
# metrics.DETECTION_COUNTERS set, joined rule-side): the verdict reads
# the per-node `detect.<counter>.<node>` shadows through this table to
# name observers.
_RULE_EVIDENCE_COUNTERS = {
    "equivocation": "primary.equivocations_detected",
    "invalid_signature": "primary.invalid_signatures",
    "stale_replay": "primary.stale_messages",
    "garbage_batches": "worker.garbage_batches",
    "helper_abuse": "worker.helper_rejected_requests",
}


def _effective_rule(commit_rule: Optional[str]) -> str:
    """The rule the committee actually ran: None defers to the
    NARWHAL_COMMIT_RULE env knob inside Consensus, and the artifact must
    record that resolution, not assume classic."""
    from ..consensus import resolve_commit_rule

    return resolve_commit_rule(commit_rule)


def sim_parameters(scenario: FaultScenario) -> Parameters:
    """Scenario parameters with the sim profile applied: committees past
    N=10 stretch the round cadence so a 60-virtual-second scenario stays
    inside single-digit wall seconds (protocol WORK is real CPU even
    under a virtual clock — only waiting compresses).  Explicit
    ``parameters`` overrides in the spec always win."""
    defaults: Dict[str, int] = {"batch_size": 50_000}
    if scenario.nodes > 10:
        # Large-committee cadence: a WAN committee of N=20+ under real
        # crypto runs multi-second rounds anyway; frame volume per round
        # is N², so this is where the wall budget goes.
        defaults.update(
            max_header_delay=5_000, max_batch_delay=3_000,
            sync_retry_delay=6_000,
        )
    elif scenario.nodes > 4:
        defaults.update(max_header_delay=500, max_batch_delay=400)
    defaults.update(scenario.parameters)
    return Parameters(**defaults)


def _health_env(scenario: FaultScenario, params: Parameters) -> Dict[str, str]:
    """Health thresholds for the sim run: the scenario's env block, with
    the cadence-sensitive windows floored proportionally to the round
    period.  The stock defaults assume ~100 ms rounds; under the
    stretched large-committee cadence a 10 s commit-stall threshold is
    only ~4 rounds and the boot window alone trips it, and a 6 s
    vote-silence window cannot see the 3 rounds of progress its rule
    requires — the thresholds must scale with the clock they watch."""
    period_s = max(0.1, params.max_header_delay / 1000.0)
    batch_s = max(0.1, params.max_batch_delay / 1000.0)
    env = dict(scenario.env)

    from ..utils.env import env_float

    def floor(key: str, minimum: float) -> None:
        # Effective value = scenario override or the registry default;
        # the floor only ever RAISES it (a scenario that pinned a low
        # window for its detection contract keeps it at small N, where
        # the minima do not bind).
        current = float(env_float(key, env=env))
        if current < minimum:
            env[key] = str(minimum)

    floor("NARWHAL_HEALTH_COMMIT_STALL_S", 8 * period_s + 4)
    floor("NARWHAL_HEALTH_VOTE_SILENCE_WINDOW_S", 5 * period_s)
    floor("NARWHAL_HEALTH_QUORUM_WEDGE_S", 5 * batch_s + 4)
    return env


def sim_keypairs(scenario: FaultScenario) -> List[KeyPair]:
    """Deterministic identities from the scenario seed (schedule seeds
    must not perturb them: commit digests are part of the bit-repro
    contract)."""
    return [
        KeyPair.generate(
            hashlib.sha256(
                f"narwhal-sim:{scenario.seed}:{i}".encode()
            ).digest()
        )
        for i in range(scenario.nodes)
    ]


def build_sim_committee(
    keypairs: List[KeyPair], workers: int, base_port: int = 40_000
) -> Committee:
    """Address-shaped committee for the in-memory transport (the
    host:port strings are pure routing keys — nothing binds them)."""
    authorities = {}
    port = base_port
    for kp in keypairs:
        def addr() -> str:
            nonlocal port
            a = f"127.0.0.1:{port}"
            port += 1
            return a

        primary = PrimaryAddresses(
            primary_to_primary=addr(), worker_to_primary=addr()
        )
        ws = {
            wid: WorkerAddresses(
                transactions=addr(),
                worker_to_worker=addr(),
                primary_to_worker=addr(),
            )
            for wid in range(workers)
        }
        authorities[kp.name] = Authority(stake=1, primary=primary, workers=ws)
    return Committee(authorities)


def _tx(counter: int, size: int) -> bytes:
    """Filler transaction (byte0=1 + u64 counter, zero-padded), framed."""
    body = bytes([1]) + counter.to_bytes(8, "little")
    return frame(body + bytes(max(0, size - len(body))))


def deterministic_blob(artifact: dict) -> bytes:
    """The bit-reproducibility surface of a sim artifact: everything
    except the wall-clock sections, canonically serialized.  Two runs of
    the same (seed, spec) must produce byte-identical blobs.  ``queues``
    is excluded with ``wall``: its put-wait/residence histograms measure
    host time (time.monotonic), not virtual time — the counts are
    schedule-determined but the means are wall noise."""
    core = {k: v for k, v in artifact.items() if k not in ("wall", "queues")}
    return json.dumps(core, sort_keys=True, separators=(",", ":")).encode()


def run_sim_scenario(
    scenario: FaultScenario,
    run_seed: int,
    workdir: str,
    parameters: Optional[Parameters] = None,
    consensus_cls_by_node: Optional[Dict[int, type]] = None,
    rate_cap: int = _RATE_CAP,
    max_virtual_s: Optional[float] = None,
    commit_rule: Optional[str] = None,
    large_n_rate_cap: Optional[int] = 60,
    clock_skew_ms: Optional[Dict[int, float]] = None,
) -> dict:
    """Run one scenario arm in simulation; returns the artifact dict
    (see module docstring).  ``consensus_cls_by_node`` swaps a node's
    Consensus runner (the planted-mutation arms).  ``commit_rule``
    selects the consensus commit rule for the WHOLE committee (the
    flag-flip sweep's arm knob); each node's audit segment records it,
    so the safety replay judges against the matching frozen oracle with
    no further plumbing.  ``large_n_rate_cap`` is the extra offered-load
    clamp applied above 10 nodes (wall cost of the sim is linear in
    frames); the knee matrix passes ``None`` to sweep real rates at
    N=10/20.  ``clock_skew_ms`` maps authority index → injected wall-
    clock skew: that authority's whole plane (primary + workers) stamps
    traces and ACKs with a clock running that far ahead/behind the
    virtual truth — the skew-injection arm that validates the clocksync
    correction against known ground truth (the protocol itself never
    reads the wall clock, so the schedule is skew-invariant)."""
    import os
    import shutil

    # Fresh workdir per run: AuditWriter rolls to `<path>.N` when a
    # segment file already exists, so judging a reused directory would
    # silently replay the PREVIOUS run's segments under this run's name.
    shutil.rmtree(workdir, ignore_errors=True)
    os.makedirs(workdir, exist_ok=True)
    params = sim_parameters(scenario) if parameters is None else parameters
    keypairs = sim_keypairs(scenario)
    names = [kp.name for kp in keypairs]
    committee = build_sim_committee(keypairs, scenario.workers)
    wan_table = compile_wan(scenario, committee, names)
    backoff_cap = float(scenario.env.get("NARWHAL_NET_BACKOFF_MAX_S", 60.0))

    plans: Dict[int, ByzantinePlan] = {}
    for b in scenario.byzantine:
        plans[b.node] = ByzantinePlan(
            behaviors=b.behaviors,
            seed=scenario.seed ^ (b.node + 1),
            withhold_targets=(
                {names[t] for t in b.targets} if b.targets else None
            ),
            replay_interval_ms=b.replay_interval_ms,
            flood_interval_ms=b.flood_interval_ms,
            garbage_bytes=b.garbage_bytes,
        )

    byz = set(scenario.byzantine_nodes())
    dead_forever = {c.node for c in scenario.crash if c.restart_at_s is None}
    honest = [i for i in range(scenario.nodes) if i not in byz]
    survivors = [i for i in honest if i not in dead_forever]
    settle_s = 0.0
    for c in scenario.crash:
        settle_s = max(
            settle_s,
            (c.restart_at_s + _RESTART_SETTLE_S)
            if c.restart_at_s is not None
            else c.at_s,
        )
    if scenario.wan:
        for part in scenario.wan.partitions:
            if part.until_s is not None:
                settle_s = max(settle_s, part.until_s + _HEAL_SETTLE_S)

    # Offered load scales DOWN with committee size: the sim's subject is
    # schedule/fault diversity, wall cost is linear in frames, and the
    # batch plane broadcasts every seal to N-1 peers.
    rate = min(scenario.rate, rate_cap)
    if scenario.nodes > 10 and large_n_rate_cap is not None:
        rate = min(rate, large_n_rate_cap)
    audit_segments: Dict[int, List[str]] = {}
    commits: Dict[int, List] = {i: [] for i in range(scenario.nodes)}
    monitor_events: List[dict] = []

    # Cross-run isolation: zero the shared registry and collect the
    # previous run's dead components out of the metrics WeakSets before
    # anything records — a stale synchronizer's pending entry must not
    # leak into this run's batch_withholding input.
    reg = metrics.registry()
    reg.reset()
    # reset() deliberately keeps instrument IDENTITY (module-level code
    # holds direct references), but per-PEER families are keyed by the
    # previous run's committee — and a zeroed `primary.peer_votes.<x>`
    # counter for a peer that no longer exists reads as a vote-silent
    # validator to the health rules (a measured false-FIRING source in
    # back-to-back sweeps).  Those names are only ever fetched at
    # component construction, never bound at import, so dropping them
    # is safe; the next run re-creates its own.
    for pool in (reg.counters, reg.gauges, reg.histograms):
        for name in [
            n for n in pool
            if n.startswith(
                (
                    "primary.peer_votes.",
                    "primary.quorum_straggler.",
                    "consensus.support_straggler.",
                    "net.reliable.peer.",
                    "clock.",
                    "detect.",
                )
            )
        ]:
            del pool[name]
    # Clock-offset estimators are keyed by the previous run's committee
    # too, and a retained smoothed estimate would leak into this run.
    clocksync.reset_estimators()
    gc.collect()
    random.seed(scenario.seed ^ (run_seed * 2654435761))

    transport = SimTransport(
        seed=scenario.seed ^ run_seed,
        wan_table=wan_table,
        backoff_cap_s=backoff_cap,
    )

    async def main() -> dict:
        import asyncio

        from ..node import spawn_primary_node, spawn_worker_node
        from ..store import Store

        loop = asyncio.get_running_loop()
        start = loop.time()
        transport.anchor(start)
        # Wall stamps (trace tables, ACK clock stamps) ride the virtual
        # clock — deterministic per (seed, spec) — plus each node's
        # injected skew; uninstalled in the run's outer finally.
        set_wall_base(loop.time)

        prim_stores = {i: Store(None) for i in range(scenario.nodes)}
        worker_stores = {
            (i, wid): Store(None)
            for i in range(scenario.nodes)
            for wid in range(scenario.workers)
        }
        primaries: Dict[int, object] = {}
        worker_nodes: Dict[int, List[object]] = {}
        incarnation: Dict[int, int] = {}

        def auth_addresses(i: int) -> List[str]:
            auth = committee.authorities[names[i]]
            out = [
                auth.primary.primary_to_primary,
                auth.primary.worker_to_primary,
            ]
            for w in auth.workers.values():
                out += [
                    w.transactions, w.worker_to_worker, w.primary_to_worker
                ]
            return out

        async def spawn_authority(i: int, replay: bool) -> None:
            inc = incarnation.get(i, 0)
            incarnation[i] = inc + 1
            audit = os.path.join(workdir, f"audit-primary-{i}.seg{inc}.bin")
            audit_segments.setdefault(i, []).append(audit)
            plan = plans.get(i)
            # One injected skew per AUTHORITY (primary + its workers):
            # the physical model is one mis-synced host per validator.
            skew_s = (clock_skew_ms or {}).get(i, 0.0) / 1000.0
            # node_scope: detection counters built by this authority's
            # components also feed per-node `detect.*` shadows, so the
            # verdict can name WHICH validator observed the evidence (the
            # one registry is otherwise committee-aggregated).
            with transport.node(f"primary-{i}"), reg.node_scope(
                f"primary-{i}"
            ), skew_scope(skew_s):
                primaries[i] = await spawn_primary_node(
                    keypairs[i],
                    committee,
                    params,
                    on_commit=(
                        lambda cert, i=i: commits[i].append(
                            (loop.time(), cert)
                        )
                    ),
                    fault_plan=plan,
                    audit_path=audit,
                    store=prim_stores[i],
                    consensus_cls=(consensus_cls_by_node or {}).get(i),
                    replay_persisted=replay,
                    commit_rule=commit_rule,
                    # Mutated nodes get depth-1 consensus channels so
                    # every commit-burst put genuinely suspends — the
                    # forcing without which a planted await-window race
                    # can never open (race_explore's pipeline applies
                    # the same).
                    channel_capacity=(
                        1 if i in (consensus_cls_by_node or {}) else None
                    ),
                )
            ws = []
            for wid in range(scenario.workers):
                # Worker-plane evidence is attributed to its AUTHORITY
                # (the verdict's node names are primary-<i>).
                with transport.node(f"worker-{i}-{wid}"), reg.node_scope(
                    f"primary-{i}"
                ), skew_scope(skew_s):
                    ws.append(
                        await spawn_worker_node(
                            keypairs[i],
                            wid,
                            committee,
                            params,
                            fault_plan=plan,
                            store=worker_stores[(i, wid)],
                        )
                    )
            worker_nodes[i] = ws

        async def crash_authority(i: int) -> None:
            transport.set_down(auth_addresses(i))
            node = primaries.pop(i, None)
            if node is not None:
                await node.shutdown()
                if node.consensus is not None and node.consensus._audit:
                    node.consensus._audit.close()
            for w in worker_nodes.pop(i, []):
                await w.shutdown()

        for i in range(scenario.nodes):
            await spawn_authority(i, replay=False)

        # Health monitor on the virtual clock; thresholds come from the
        # scenario's env block (injected, never os.environ).
        monitor = HealthMonitor(
            reg,
            rules=default_rules(env=_health_env(scenario, params)),
            interval_s=1.0,
        )
        reg.health = monitor

        async def health_driver() -> None:
            while True:
                await asyncio.sleep(monitor.interval_s)
                monitor.evaluate(now=loop.time())

        health_task = spawn(health_driver(), name="sim-health")

        # Clients: one per worker, paced on the virtual clock.  Filler
        # txs only — liveness is judged on payload-batch commits, not
        # parsed latency samples.
        stop_clients = asyncio.Event()
        per_client = max(1, rate // max(1, scenario.nodes * scenario.workers))

        async def client(i: int, wid: int, idx: int) -> None:
            address = committee.worker(names[i], wid).transactions
            counter = idx << 40
            burst = max(1, per_client // 2)
            conn = None
            while not stop_clients.is_set():
                if conn is None or conn.transport.closed:
                    try:
                        conn = transport.open_tx_connection(address)
                    except OSError:
                        await asyncio.sleep(1.0)  # crashed worker: retry
                        continue
                chunk = b"".join(
                    _tx(counter + k, scenario.tx_size) for k in range(burst)
                )
                counter += burst
                conn.write(chunk)
                await asyncio.sleep(0.5)

        client_tasks = [
            spawn(client(i, wid, i * scenario.workers + wid),
                  name="sim-client")
            for i in range(scenario.nodes)
            for wid in range(scenario.workers)
        ]

        # Fault timeline (virtual offsets from the launch anchor).
        events = sorted(
            [("crash", c.at_s, c.node) for c in scenario.crash]
            + [
                ("restart", c.restart_at_s, c.node)
                for c in scenario.crash
                if c.restart_at_s is not None
            ],
            key=lambda e: e[1],
        )
        for kind, at_s, node_i in events:
            delay = (start + at_s) - loop.time()
            if delay > 0:
                await asyncio.sleep(delay)
            if kind == "crash":
                await crash_authority(node_i)
            else:
                transport.set_up(auth_addresses(node_i))
                await spawn_authority(node_i, replay=True)

        remaining = (start + scenario.duration) - loop.time()
        if remaining > 0:
            await asyncio.sleep(remaining)

        settle_ts = start + settle_s

        def payload_commits_after(i: int, ts: float) -> int:
            return sum(
                1
                for t, cert in commits[i]
                if t >= ts and cert.header.payload
            )

        # Virtual-time liveness grace: cheap to grant, bounded by the
        # scenario's progress_wait.
        grace_deadline = loop.time() + scenario.progress_wait
        while loop.time() < grace_deadline:
            if all(payload_commits_after(i, settle_ts) > 0 for i in survivors):
                break
            await asyncio.sleep(1.0)

        stop_clients.set()
        for t in client_tasks:
            t.cancel()
        health_task.cancel()
        monitor.evaluate(now=loop.time())
        monitor_events.extend(monitor.events)

        for i in list(primaries):
            node = primaries.pop(i)
            await node.shutdown()
            if node.consensus is not None and node.consensus._audit:
                node.consensus._audit.close()
        for i in list(worker_nodes):
            for w in worker_nodes.pop(i):
                await w.shutdown()
        await transport.shutdown()
        await asyncio.gather(
            *client_tasks, health_task, return_exceptions=True
        )
        return {
            "settle_ts": settle_ts,
            "start": start,
            "liveness_nodes": {
                f"primary-{i}": {
                    "payload_commits_post_settle": payload_commits_after(
                        i, settle_ts
                    ),
                    "ok": payload_commits_after(i, settle_ts) > 0,
                }
                for i in survivors
            },
        }

    from ..primary.messages import set_decode_cache

    import asyncio

    net_seam.install(transport)
    set_sim_mac(True)
    set_decode_cache(True)
    # Certificate-signature scheme per scenario (the sim arm of the
    # --cert-sig-scheme A/B): a NARWHAL_CERT_SIG_SCHEME entry in the
    # scenario's env dict scopes the scheme to this run; absent, the
    # harness/process setting stands.  Saved/restored like the sim-MAC
    # bracket so sweeps with mixed arms can't leak a scheme.
    from ..crypto.aggregate import (
        resolve_scheme as _resolve_cert_scheme,
        scheme_override as _cert_scheme_override,
        set_scheme as _set_cert_scheme,
    )

    prev_cert_scheme = _cert_scheme_override()
    scenario_scheme = scenario.env.get("NARWHAL_CERT_SIG_SCHEME")
    if scenario_scheme is not None:
        _set_cert_scheme(_resolve_cert_scheme(str(scenario_scheme)))
    timed_out = False
    try:
        try:
            result, stats = run_virtual(
                main, run_seed, max_virtual_s=max_virtual_s
            )
        # asyncio.TimeoutError: on 3.10 it is NOT the builtin
        # TimeoutError (they merged in 3.11), and a bare `except
        # TimeoutError` would let the guard crash the whole sweep.
        except (TimeoutError, asyncio.TimeoutError):
            # A livelocked/deadlocked scenario: deterministic by seed —
            # itself a finding, judged below on whatever was recorded.
            timed_out = True
            result, stats = None, {
                "seed": run_seed, "ticks": 0, "permutations": 0,
                "jumps": 0, "capped_jumps": 0, "virtual_s": None,
                "wall_s": None, "compression": None,
            }
    finally:
        set_sim_mac(False)
        set_decode_cache(False)
        _set_cert_scheme(prev_cert_scheme)
        set_wall_base(None)
        net_seam.reset()
        reg.health = None

    # -- verdicts (sync, outside the loop) ------------------------------------

    safety_nodes: Dict[str, dict] = {}
    sequences: Dict[str, List[str]] = {}
    for i in honest:
        verdict = replay_segments(
            committee, params.gc_depth, audit_segments.get(i, [])
        )
        sequences[f"primary-{i}"] = verdict.pop("commit_digests")
        safety_nodes[f"primary-{i}"] = verdict
    cross = cross_node_prefix(sequences)
    safety = {
        "ok": cross["ok"] and all(v["ok"] for v in safety_nodes.values()),
        "nodes": safety_nodes,
        "cross_node": cross,
    }

    liveness = {
        "ok": (
            not timed_out
            and result is not None
            and bool(result["liveness_nodes"])
            and all(v["ok"] for v in result["liveness_nodes"].values())
        ),
        "settle_offset_s": settle_s,
        "nodes": result["liveness_nodes"] if result else {},
        "timed_out": timed_out,
    }

    fired = sorted(
        {
            e["rule"]
            for e in monitor_events
            if e.get("event") == "FIRING"
        }
    )
    missing = [r for r in scenario.expect_rules if r not in fired]
    # Per-node attribution: counter-backed rules name the validator(s)
    # whose components observed the evidence (the `detect.*` shadows fed
    # via Registry.node_scope).  Gauge- and per-peer-backed rules have no
    # single observing counter and stay committee-level.
    observers: Dict[str, List[str]] = {}
    for rule, counter_name in _RULE_EVIDENCE_COUNTERS.items():
        prefix = f"detect.{counter_name}."
        seen = sorted(
            name[len(prefix):]
            for name, c in reg.counters.items()
            if name.startswith(prefix) and c.value > 0
        )
        if seen:
            observers[rule] = seen
    detection = {
        "ok": not missing,
        "expected": scenario.expect_rules,
        "fired": fired,
        "missing": missing,
        "observers": observers,
    }
    if scenario.is_clean():
        detection["ok"] = not fired
        detection["expected"] = []

    # Virtual-time cert→commit: the committee-aggregated
    # consensus.cert_to_commit_seconds histogram rides the virtual clock
    # here, so its mean is pure protocol cadence (commit depth × round
    # period) with zero host noise — the series that prices a
    # commit-rule latency claim before any socketed run.
    c2c = reg.histograms.get("consensus.cert_to_commit_seconds")
    cert_to_commit = {
        "count": c2c.count if c2c is not None else 0,
        "mean_virtual_s": (
            round(c2c.sum / c2c.count, 6)
            if c2c is not None and c2c.count
            else None
        ),
    }
    # Same virtual clock, consensus side: per-leader first→quorum-th
    # direct-support arrival spread (ms) — the multi-leader flip's
    # before-number at N=10/20 with zero host noise.
    sa = reg.histograms.get("consensus.support_arrival_ms")
    support_arrival = {
        "count": sa.count if sa is not None else 0,
        "mean_virtual_ms": (
            round(sa.sum / sa.count, 3)
            if sa is not None and sa.count
            else None
        ),
    }
    # Clock-offset estimation, judged against injected ground truth: the
    # sim's channels feed per-(source node, destination address) offset
    # estimators (clocksync — the shared registry cannot carry per-node
    # gauges), mapped back to authorities here and reconciled with the
    # SAME zero-mean formula metrics_check applies to live snapshots.
    # Everything rides the virtual clock, so the section is part of the
    # deterministic blob: offsets are bit-reproducible per (seed, spec).
    addr_to_auth: Dict[str, int] = {}
    for i, nm in enumerate(names):
        auth = committee.authorities[nm]
        addr_to_auth[auth.primary.primary_to_primary] = i
        addr_to_auth[auth.primary.worker_to_primary] = i
        for w in auth.workers.values():
            for a in (
                w.transactions, w.worker_to_worker, w.primary_to_worker
            ):
                addr_to_auth[a] = i

    def _label_auth(label: str) -> Optional[int]:
        parts = label.split("-")
        if parts[0] in ("primary", "worker") and len(parts) > 1:
            try:
                return int(parts[1])
            except ValueError:
                return None
        return None

    pairwise: Dict[int, Dict[int, List[float]]] = {}
    for src_label, peers in clocksync.offsets_by_source().items():
        s = _label_auth(src_label)
        if s is None:
            continue
        for addr, info in peers.items():
            d = addr_to_auth.get(addr)
            if d is None or d == s:
                continue
            pairwise.setdefault(s, {}).setdefault(d, []).append(
                info["offset_ms"]
            )
    peer_offsets_ms = {
        f"primary-{s}": {
            f"primary-{d}": round(sum(v) / len(v), 3)
            for d, v in sorted(peers.items())
        }
        for s, peers in sorted(pairwise.items())
    }
    clock = {
        "injected_skew_ms": {
            f"primary-{i}": v
            for i, v in sorted((clock_skew_ms or {}).items())
        },
        "peer_offsets_ms": peer_offsets_ms,
        "reconciled_ms": {
            node: round(v, 3)
            for node, v in clocksync.reconcile_zero_mean(
                peer_offsets_ms
            ).items()
        },
    }

    # Quorum-straggler attribution over the shared registry, with the
    # per-address counters folded back to authority labels.  Counts are
    # schedule-determined — also inside the deterministic blob.
    stragglers: Dict[str, Dict[str, int]] = {}
    for section, prefix in (
        ("quorum", "primary.quorum_straggler."),
        ("support", "consensus.support_straggler."),
    ):
        agg: Dict[str, int] = {}
        for counter_name, c in reg.counters.items():
            if counter_name.startswith(prefix) and c.value > 0:
                idx = addr_to_auth.get(counter_name[len(prefix):])
                label = (
                    f"primary-{idx}"
                    if idx is not None
                    else counter_name[len(prefix):]
                )
                agg[label] = agg.get(label, 0) + c.value
        stragglers[section] = dict(sorted(agg.items()))

    # Per-channel backpressure accounting over the shared registry: the
    # sim runs the whole committee in one process, so channel series
    # aggregate committee-wide (same convention as the queue-depth
    # gauge_fns).  No scrape timeline here — first_saturating uses the
    # high-water fallback.  The join lives in the bench package; a
    # deployment that ships only narwhal_tpu simply omits the section.
    try:
        from benchmark.metrics_check import queue_pressure_summary
    except ImportError:
        queues = {}
    else:
        queues = queue_pressure_summary(
            [reg.snapshot(include_trace=False)]
        )

    artifact = {
        "name": scenario.name,
        "generated_by": "narwhal_tpu/sim",
        "nodes": scenario.nodes,
        "workers": scenario.workers,
        "scenario_seed": scenario.seed,
        "run_seed": run_seed,
        "sim_rate": rate,
        "commit_rule": _effective_rule(commit_rule),
        "cert_to_commit": cert_to_commit,
        "support_arrival": support_arrival,
        "clock": clock,
        "stragglers": stragglers,
        "queues": queues,
        "parameters": params.to_json(),
        "verdicts": {
            "safety": safety,
            "liveness": liveness,
            "detection": detection,
        },
        "ok": safety["ok"] and liveness["ok"] and detection["ok"],
        "commit_sequences": sequences,
        "events": [
            {
                "event": e["event"],
                "rule": e["rule"],
                "subject": e["subject"],
                "t": e["t"],
            }
            for e in monitor_events
        ],
        "schedule": {
            k: stats[k]
            for k in ("seed", "ticks", "permutations", "jumps", "virtual_s")
        },
        # Wall-clock section: EXCLUDED from deterministic_blob().
        "wall": {
            "wall_s": stats["wall_s"],
            "compression": stats["compression"],
            "capped_jumps": stats["capped_jumps"],
        },
    }
    return artifact
