"""Wire messages of the worker plane and the worker↔primary LAN plane.

Reference enums: `WorkerMessage` (worker/src/worker.rs:36-40),
`PrimaryWorkerMessage` (primary/src/primary.rs:41-47), `WorkerPrimaryMessage`
(primary/src/primary.rs:50-56).  Each plane has its own socket, so tag spaces
are independent.  Encoding: u8 tag + canonical serde body.

The primary↔primary plane (Header/Vote/Certificate) lives in
narwhal_tpu.primary.messages.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Tuple

from .crypto import Digest, PublicKey
from .utils.serde import Reader, Writer

Transaction = bytes
Batch = List[Transaction]
Round = int
WorkerId = int


# --- worker ↔ worker ---------------------------------------------------------

WORKER_BATCH = 0
WORKER_BATCH_REQUEST = 1


def encode_batch(batch: Batch) -> bytes:
    """WorkerMessage::Batch — THE hot serialization path (≈500 kB frames)."""
    w = Writer()
    w.u8(WORKER_BATCH)
    w.u32(len(batch))
    for tx in batch:
        w.bytes(tx)
    return w.finish()


def decode_batch_body(r: Reader) -> Batch:
    n = r.u32()
    return [r.bytes() for _ in range(n)]


def encode_batch_request(digests: List[Digest], requestor: PublicKey) -> bytes:
    w = Writer()
    w.u8(WORKER_BATCH_REQUEST)
    w.u32(len(digests))
    for d in digests:
        w.raw(d)
    w.raw(requestor)
    return w.finish()


def decode_worker_message(data: bytes):
    """Returns ("batch", Batch) | ("batch_request", digests, requestor)."""
    r = Reader(data)
    tag = r.u8()
    if tag == WORKER_BATCH:
        batch = decode_batch_body(r)
        r.expect_done()
        return ("batch", batch)
    if tag == WORKER_BATCH_REQUEST:
        n = r.u32()
        digests = [Digest(r.raw(32)) for _ in range(n)]
        requestor = PublicKey(r.raw(32))
        r.expect_done()
        return ("batch_request", digests, requestor)
    raise ValueError(f"unknown WorkerMessage tag {tag}")


# --- primary → worker (LAN) --------------------------------------------------

PW_SYNCHRONIZE = 0
PW_CLEANUP = 1


def encode_synchronize(digests: List[Digest], target: PublicKey) -> bytes:
    w = Writer()
    w.u8(PW_SYNCHRONIZE)
    w.u32(len(digests))
    for d in digests:
        w.raw(d)
    w.raw(target)
    return w.finish()


def encode_cleanup(round: Round) -> bytes:
    return Writer().u8(PW_CLEANUP).u64(round).finish()


def decode_primary_worker_message(data: bytes):
    """Returns ("synchronize", digests, target) | ("cleanup", round)."""
    r = Reader(data)
    tag = r.u8()
    if tag == PW_SYNCHRONIZE:
        n = r.u32()
        digests = [Digest(r.raw(32)) for _ in range(n)]
        target = PublicKey(r.raw(32))
        r.expect_done()
        return ("synchronize", digests, target)
    if tag == PW_CLEANUP:
        rnd = r.u64()
        r.expect_done()
        return ("cleanup", rnd)
    raise ValueError(f"unknown PrimaryWorkerMessage tag {tag}")


# --- worker → primary (LAN) --------------------------------------------------

WP_OUR_BATCH = 0
WP_OTHERS_BATCH = 1


@dataclass(frozen=True)
class BatchDigestMessage:
    digest: Digest
    worker_id: WorkerId
    ours: bool


def encode_batch_digest(digest: Digest, worker_id: WorkerId, ours: bool) -> bytes:
    w = Writer()
    w.u8(WP_OUR_BATCH if ours else WP_OTHERS_BATCH)
    w.raw(digest)
    w.u32(worker_id)
    return w.finish()


def decode_worker_primary_message(data: bytes) -> BatchDigestMessage:
    r = Reader(data)
    tag = r.u8()
    if tag not in (WP_OUR_BATCH, WP_OTHERS_BATCH):
        raise ValueError(f"unknown WorkerPrimaryMessage tag {tag}")
    digest = Digest(r.raw(32))
    worker_id = r.u32()
    r.expect_done()
    return BatchDigestMessage(digest, worker_id, tag == WP_OUR_BATCH)


# --- wire-type classification (wire-goodput ledger) --------------------------
#
# Each plane has its own socket and an independent u8 tag space, so a
# frame's message type is (plane, first byte).  The receivers hand their
# plane's classifier to network.Receiver, which accounts every inbound
# frame per type in the metrics WireLedger; senders pass the type
# explicitly at the call site that just encoded the message.  One shared
# name space across planes (a "batch" is a batch whichever socket carried
# it) so the bench's wire section aggregates cleanly.

WORKER_FRAME_TYPES = {
    WORKER_BATCH: "batch",
    WORKER_BATCH_REQUEST: "batch_request",
}

PRIMARY_WORKER_FRAME_TYPES = {
    PW_SYNCHRONIZE: "synchronize",
    PW_CLEANUP: "cleanup",
}

WORKER_PRIMARY_FRAME_TYPES = {
    WP_OUR_BATCH: "batch_digest",
    WP_OTHERS_BATCH: "batch_digest",
}


def frame_classifier(tag_map):
    """A ``bytes -> type-name`` classifier over one plane's tag space
    (unknown/empty frames classify as "unknown", never raise — the
    ledger must account garbage too, the handler rejects it later)."""

    def classify(data: bytes) -> str:
        if not data:
            return "unknown"
        return tag_map.get(data[0], "unknown")

    return classify
