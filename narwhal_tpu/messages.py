"""Wire messages of the worker plane and the worker↔primary LAN plane.

Reference enums: `WorkerMessage` (worker/src/worker.rs:36-40),
`PrimaryWorkerMessage` (primary/src/primary.rs:41-47), `WorkerPrimaryMessage`
(primary/src/primary.rs:50-56).  Each plane has its own socket, so tag spaces
are independent.  Encoding: u8 tag + canonical serde body.

The primary↔primary plane (Header/Vote/Certificate) lives in
narwhal_tpu.primary.messages.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Tuple

from .crypto import Digest, PublicKey
from .network import wirev2
from .utils.serde import Reader, Writer

Transaction = bytes
Batch = List[Transaction]
Round = int
WorkerId = int


# --- wire-v2 codec context ----------------------------------------------------
#
# The committee roster is the one piece of shared state both ends of
# every connection provably hold (it IS the deployment), so wire v2
# encodes public keys as committee indices: a varint key-ref where 0
# escapes to a literal 32-byte key (unknown/Byzantine-minted keys — the
# wrong_key fault arm — still encode; they just don't compress) and
# v >= 1 names committee member v-1 in sorted-key order, which is
# identical across processes loading the same committee file.  Installed
# at node boot (Primary.spawn / Worker.spawn); encoders fall back to
# literals when no committee is installed, so unit-test roundtrips work
# without one.

_WIRE_KEYS: List[PublicKey] = []
_WIRE_INDEX: Dict[PublicKey, int] = {}


def set_wire_committee(committee) -> None:
    """Install the committee roster as the wire-v2 key-index space."""
    global _WIRE_KEYS, _WIRE_INDEX
    _WIRE_KEYS = [PublicKey(name) for name in sorted(committee.authorities)]
    _WIRE_INDEX = {k: i for i, k in enumerate(_WIRE_KEYS)}


def write_key_ref(w: Writer, key: PublicKey) -> None:
    i = _WIRE_INDEX.get(key)
    if i is None:
        w.uvarint(0)
        w.raw(key)
    else:
        w.uvarint(i + 1)


def read_key_ref(r: Reader) -> PublicKey:
    v = r.uvarint()
    if v == 0:
        return PublicKey(r.raw(32))
    try:
        return _WIRE_KEYS[v - 1]
    except IndexError:
        raise ValueError(
            f"wire key index {v - 1} outside committee "
            f"({len(_WIRE_KEYS)} keys installed)"
        ) from None


def skip_key_ref(r: Reader, spans: List[int]) -> None:
    """Span-walker helper: step over one key-ref, recording a literal
    key's offset as dictionary material."""
    if r.uvarint() == 0:
        spans.append(r.tell())
        r.raw(32)


# --- worker ↔ worker ---------------------------------------------------------

WORKER_BATCH = 0
WORKER_BATCH_REQUEST = 1


def encode_batch(batch: Batch) -> bytes:
    """WorkerMessage::Batch — THE hot serialization path (≈500 kB frames)."""
    w = Writer()
    w.u8(WORKER_BATCH)
    w.u32(len(batch))
    for tx in batch:
        w.bytes(tx)
    return w.finish()


def decode_batch_body(r: Reader) -> Batch:
    n = r.u32()
    return [r.bytes() for _ in range(n)]


def encode_batch_request(digests: List[Digest], requestor: PublicKey) -> bytes:
    w = Writer()
    w.u8(WORKER_BATCH_REQUEST)
    if wirev2.enabled():
        w.uvarint(len(digests))
        for d in digests:
            w.raw(d)
        write_key_ref(w, requestor)
    else:
        w.u32(len(digests))
        for d in digests:
            w.raw(d)
        w.raw(requestor)
    return w.finish()


def decode_worker_message(data: bytes):
    """Returns ("batch", Batch) | ("batch_request", digests, requestor)."""
    r = Reader(data)
    tag = r.u8()
    if tag == WORKER_BATCH:
        batch = decode_batch_body(r)
        r.expect_done()
        return ("batch", batch)
    if tag == WORKER_BATCH_REQUEST:
        if wirev2.enabled():
            n = r.uvarint()
            digests = [Digest(r.raw(32)) for _ in range(n)]
            requestor = read_key_ref(r)
        else:
            n = r.u32()
            digests = [Digest(r.raw(32)) for _ in range(n)]
            requestor = PublicKey(r.raw(32))
        r.expect_done()
        return ("batch_request", digests, requestor)
    raise ValueError(f"unknown WorkerMessage tag {tag}")


# --- primary → worker (LAN) --------------------------------------------------

PW_SYNCHRONIZE = 0
PW_CLEANUP = 1


def encode_synchronize(digests: List[Digest], target: PublicKey) -> bytes:
    w = Writer()
    w.u8(PW_SYNCHRONIZE)
    if wirev2.enabled():
        w.uvarint(len(digests))
        for d in digests:
            w.raw(d)
        write_key_ref(w, target)
    else:
        w.u32(len(digests))
        for d in digests:
            w.raw(d)
        w.raw(target)
    return w.finish()


def encode_cleanup(round: Round) -> bytes:
    w = Writer().u8(PW_CLEANUP)
    if wirev2.enabled():
        w.uvarint(round)
    else:
        w.u64(round)
    return w.finish()


def decode_primary_worker_message(data: bytes):
    """Returns ("synchronize", digests, target) | ("cleanup", round)."""
    r = Reader(data)
    tag = r.u8()
    v2 = wirev2.enabled()
    if tag == PW_SYNCHRONIZE:
        if v2:
            n = r.uvarint()
            digests = [Digest(r.raw(32)) for _ in range(n)]
            target = read_key_ref(r)
        else:
            n = r.u32()
            digests = [Digest(r.raw(32)) for _ in range(n)]
            target = PublicKey(r.raw(32))
        r.expect_done()
        return ("synchronize", digests, target)
    if tag == PW_CLEANUP:
        rnd = r.uvarint() if v2 else r.u64()
        r.expect_done()
        return ("cleanup", rnd)
    raise ValueError(f"unknown PrimaryWorkerMessage tag {tag}")


# --- worker → primary (LAN) --------------------------------------------------

WP_OUR_BATCH = 0
WP_OTHERS_BATCH = 1


@dataclass(frozen=True)
class BatchDigestMessage:
    digest: Digest
    worker_id: WorkerId
    ours: bool


def encode_batch_digest(digest: Digest, worker_id: WorkerId, ours: bool) -> bytes:
    w = Writer()
    w.u8(WP_OUR_BATCH if ours else WP_OTHERS_BATCH)
    w.raw(digest)
    if wirev2.enabled():
        w.uvarint(worker_id)
    else:
        w.u32(worker_id)
    return w.finish()


def decode_worker_primary_message(data: bytes) -> BatchDigestMessage:
    r = Reader(data)
    tag = r.u8()
    if tag not in (WP_OUR_BATCH, WP_OTHERS_BATCH):
        raise ValueError(f"unknown WorkerPrimaryMessage tag {tag}")
    digest = Digest(r.raw(32))
    worker_id = r.uvarint() if wirev2.enabled() else r.u32()
    r.expect_done()
    return BatchDigestMessage(digest, worker_id, tag == WP_OUR_BATCH)


# --- wire-type classification (wire-goodput ledger) --------------------------
#
# Each plane has its own socket and an independent u8 tag space, so a
# frame's message type is (plane, first byte).  The receivers hand their
# plane's classifier to network.Receiver, which accounts every inbound
# frame per type in the metrics WireLedger; senders pass the type
# explicitly at the call site that just encoded the message.  One shared
# name space across planes (a "batch" is a batch whichever socket carried
# it) so the bench's wire section aggregates cleanly.

WORKER_FRAME_TYPES = {
    WORKER_BATCH: "batch",
    WORKER_BATCH_REQUEST: "batch_request",
}

PRIMARY_WORKER_FRAME_TYPES = {
    PW_SYNCHRONIZE: "synchronize",
    PW_CLEANUP: "cleanup",
}

WORKER_PRIMARY_FRAME_TYPES = {
    WP_OUR_BATCH: "batch_digest",
    WP_OTHERS_BATCH: "batch_digest",
}


def frame_classifier(tag_map):
    """A ``bytes -> type-name`` classifier over one plane's tag space
    (unknown/empty frames classify as "unknown", never raise — the
    ledger must account garbage too, the handler rejects it later)."""

    def classify(data: bytes) -> str:
        if not data:
            return "unknown"
        return tag_map.get(data[0], "unknown")

    return classify


# NOTE on span walkers: only the primary↔primary message types register
# wire-v2 digest-span walkers (see primary/messages.py) — theirs is the
# traffic that rides ReliableSender, where per-connection dictionary
# compression runs.  Of this module's types, `batch` also rides
# ReliableSender but deliberately registers no walker (its payload is
# transaction data, owned by the residual-deflate path), and the rest
# (batch_request, synchronize, cleanup, batch_digest) ride SimpleSender,
# whose connections stay on legacy framing: coalesced for the syscall
# win, never dictionary-compressed — walkers here would be dead code.
