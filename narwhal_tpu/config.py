"""Committee, stakes, addresses, tunable parameters, key files.

Mirrors the reference `config` crate (config/src/lib.rs, 271 LoC):
stake-weighted `Committee` with 2f+1 / f+1 thresholds (lines 168-181), five
listen addresses per authority (112-128), `Parameters` with defaults (61-96),
and JSON import/export (28-56).
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Dict, List, Tuple

from .crypto import KeyPair, PublicKey

Stake = int
WorkerId = int


class ConfigError(Exception):
    pass


@dataclass(frozen=True)
class PrimaryAddresses:
    # Address to receive messages from other primaries (WAN).
    primary_to_primary: str
    # Address to receive messages from our workers (LAN).
    worker_to_primary: str


@dataclass(frozen=True)
class WorkerAddresses:
    # Address to receive client transactions (WAN).
    transactions: str
    # Address to receive messages from other workers (WAN).
    worker_to_worker: str
    # Address to receive messages from our primary (LAN).
    primary_to_worker: str


@dataclass
class Authority:
    stake: Stake
    primary: PrimaryAddresses
    workers: Dict[WorkerId, WorkerAddresses] = field(default_factory=dict)


class Committee:
    """The static validator set.  Reference config/src/lib.rs:130-246."""

    def __init__(self, authorities: Dict[PublicKey, Authority]) -> None:
        self.authorities = authorities

    def size(self) -> int:
        return len(self.authorities)

    def stake(self, name: PublicKey) -> Stake:
        auth = self.authorities.get(name)
        return auth.stake if auth is not None else 0

    def total_stake(self) -> Stake:
        return sum(a.stake for a in self.authorities.values())

    def quorum_threshold(self) -> Stake:
        """2f+1 votes of stake (any two quorums intersect in an honest node).
        Reference config/src/lib.rs:168-173."""
        total = self.total_stake()
        return 2 * total // 3 + 1

    def validity_threshold(self) -> Stake:
        """f+1 votes of stake (at least one honest node).
        Reference config/src/lib.rs:176-181."""
        total = self.total_stake()
        return (total + 2) // 3

    # --- address lookups (reference config/src/lib.rs:184-246) ---

    def primary(self, name: PublicKey) -> PrimaryAddresses:
        try:
            return self.authorities[name].primary
        except KeyError:
            raise ConfigError(f"unknown authority {name!r}")

    def others_primaries(self, myself: PublicKey) -> List[Tuple[PublicKey, PrimaryAddresses]]:
        return [
            (name, a.primary)
            for name, a in self.authorities.items()
            if name != myself
        ]

    def worker(self, name: PublicKey, worker_id: WorkerId) -> WorkerAddresses:
        try:
            auth = self.authorities[name]
        except KeyError:
            raise ConfigError(f"unknown authority {name!r}")
        try:
            return auth.workers[worker_id]
        except KeyError:
            raise ConfigError(f"authority {name!r} has no worker {worker_id}")

    def our_workers(self, myself: PublicKey) -> List[WorkerAddresses]:
        try:
            return list(self.authorities[myself].workers.values())
        except KeyError:
            raise ConfigError(f"unknown authority {myself!r}")

    def others_workers(
        self, myself: PublicKey, worker_id: WorkerId
    ) -> List[Tuple[PublicKey, WorkerAddresses]]:
        """Same-id workers of every other authority — the payload-sharding
        pairing (reference config/src/lib.rs:230-246)."""
        out = []
        for name, auth in self.authorities.items():
            if name == myself:
                continue
            addrs = auth.workers.get(worker_id)
            if addrs is not None:
                out.append((name, addrs))
        return out

    # --- JSON import/export ---

    def to_json(self) -> dict:
        return {
            "authorities": {
                name.encode_base64(): {
                    "stake": a.stake,
                    "primary": {
                        "primary_to_primary": a.primary.primary_to_primary,
                        "worker_to_primary": a.primary.worker_to_primary,
                    },
                    "workers": {
                        str(wid): {
                            "transactions": w.transactions,
                            "worker_to_worker": w.worker_to_worker,
                            "primary_to_worker": w.primary_to_worker,
                        }
                        for wid, w in a.workers.items()
                    },
                }
                for name, a in self.authorities.items()
            }
        }

    @classmethod
    def from_json(cls, obj: dict) -> "Committee":
        authorities: Dict[PublicKey, Authority] = {}
        for name_b64, a in obj["authorities"].items():
            name = PublicKey.decode_base64(name_b64)
            authorities[name] = Authority(
                stake=int(a["stake"]),
                primary=PrimaryAddresses(
                    primary_to_primary=a["primary"]["primary_to_primary"],
                    worker_to_primary=a["primary"]["worker_to_primary"],
                ),
                workers={
                    int(wid): WorkerAddresses(
                        transactions=w["transactions"],
                        worker_to_worker=w["worker_to_worker"],
                        primary_to_worker=w["primary_to_worker"],
                    )
                    for wid, w in a.get("workers", {}).items()
                },
            )
        return cls(authorities)

    def export(self, path: str) -> None:
        with open(path, "w") as f:
            json.dump(self.to_json(), f, indent=2, sort_keys=True)

    @classmethod
    def load(cls, path: str) -> "Committee":
        with open(path) as f:
            return cls.from_json(json.load(f))


@dataclass
class Parameters:
    """Tunables with the reference defaults (config/src/lib.rs:61-96).
    All delays in milliseconds, sizes in bytes."""

    # The preferred header size: the primary creates a header when it has
    # enough digests, or when max_header_delay passes.
    header_size: int = 1_000
    max_header_delay: int = 100
    # Sui-style round-cadence floor: when > 0, a proposer holding a parent
    # quorum proposes as soon as (a) min_header_delay has elapsed since its
    # last header AND (b) it has ANY payload — instead of riding
    # max_header_delay waiting for header_size bytes of digests.  Empty
    # rounds still wait for max_header_delay (an idle committee must not
    # spin headers at wire speed).  0 (the default) disables the fast
    # cadence and keeps the reference behavior bit-for-bit.
    min_header_delay: int = 0
    # Parent-linger window: when > 0, a proposer whose round just advanced
    # holds the next header open for this many milliseconds so parent
    # certificates arriving AFTER the round-advance quorum still get cited
    # (the Core forwards post-quorum certificates while the window is
    # open).  Without it a header's parents are exactly the FIRST 2f+1
    # certificates of the round, which leaves commit-rule slot support
    # sitting at the quorum borderline (the multileader rule's motivating
    # measurement — see consensus/tusk.py::MultiLeaderTusk).  Price it off
    # the measured consensus.support_arrival_ms headroom: a linger of
    # roughly that spread converts borderline support rounds into direct
    # commits.  max_header_delay still caps every round; 0 (the default)
    # disables the window and keeps the reference behavior bit-for-bit.
    header_linger: int = 0
    # Depth of garbage collection, in rounds.
    gc_depth: int = 50
    # Delay before retrying a sync request, and fan-out of the retry.
    sync_retry_delay: int = 5_000
    sync_retry_nodes: int = 3
    # The preferred batch size and the batch-seal timeout.
    batch_size: int = 500_000
    max_batch_delay: int = 100

    def log(self, logger) -> None:
        """Echo config at boot; the benchmark harness parses these lines back
        (reference config/src/lib.rs:100-110, benchmark logs.py:109-131)."""
        logger.info("Header size set to %s B", self.header_size)
        logger.info("Max header delay set to %s ms", self.max_header_delay)
        logger.info("Min header delay set to %s ms", self.min_header_delay)
        logger.info("Header linger set to %s ms", self.header_linger)
        logger.info("Garbage collection depth set to %s rounds", self.gc_depth)
        logger.info("Sync retry delay set to %s ms", self.sync_retry_delay)
        logger.info("Sync retry nodes set to %s nodes", self.sync_retry_nodes)
        logger.info("Batch size set to %s B", self.batch_size)
        logger.info("Max batch delay set to %s ms", self.max_batch_delay)

    def to_json(self) -> dict:
        return {
            "header_size": self.header_size,
            "max_header_delay": self.max_header_delay,
            "min_header_delay": self.min_header_delay,
            "header_linger": self.header_linger,
            "gc_depth": self.gc_depth,
            "sync_retry_delay": self.sync_retry_delay,
            "sync_retry_nodes": self.sync_retry_nodes,
            "batch_size": self.batch_size,
            "max_batch_delay": self.max_batch_delay,
        }

    @classmethod
    def from_json(cls, obj: dict) -> "Parameters":
        fields = cls().to_json().keys()
        unknown = set(obj) - set(fields)
        if unknown:
            raise ConfigError(f"unknown parameter(s): {sorted(unknown)}")
        vals = {}
        for k, v in obj.items():
            if not isinstance(v, int) or isinstance(v, bool) or v < 0:
                raise ConfigError(f"parameter {k!r} must be a non-negative integer, got {v!r}")
            vals[k] = v
        return cls(**vals)

    def export(self, path: str) -> None:
        with open(path, "w") as f:
            json.dump(self.to_json(), f, indent=2, sort_keys=True)

    @classmethod
    def load(cls, path: str) -> "Parameters":
        with open(path) as f:
            return cls.from_json(json.load(f))


def export_keypair(kp: KeyPair, path: str) -> None:
    with open(path, "w") as f:
        json.dump(kp.to_json(), f, indent=2)


def load_keypair(path: str) -> KeyPair:
    with open(path) as f:
        return KeyPair.from_json(json.load(f))
