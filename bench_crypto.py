#!/usr/bin/env python3
"""North-star microbenchmark: ed25519 signature verifications/sec/chip.

BASELINE.json names `ed25519 verifies/sec/chip` as this build's own metric.
This benchmark measures the TPU batch verifier (narwhal_tpu/ops/ed25519.py,
the device analog of the reference's dalek `verify_batch`,
/root/reference/crypto/src/lib.rs:206-219) against the CPU/OpenSSL verifier
on the same host, at batch sizes spanning the protocol's realistic range
(a 4-node certificate carries 3 sigs; a 50-node round can burst ~8k sigs
through the Core's accumulate→batch-verify seam).

Methodology:
- steady state only: first call per shape compiles (tens of seconds, then
  cached persistently via NARWHAL_JAX_CACHE); timings start after a warmup
  call per shape.
- `device`: median-of-N wall time of dispatch→block on the result mask —
  the latency a Core burst actually pays.
- `pipelined`: K batches dispatched back-to-back before blocking — the
  sustained chip rate when host prep overlaps device compute (the async
  verify path in primary/core.py works this way).
- `prep`: host-side bytes→limbs/windows + SHA-512 hash-to-scalar cost.
- CPU baseline: single-core OpenSSL verify loop (this host has 1 core;
  multiply by core count for a multi-core host figure).

Output: one JSON line per configuration plus a `summary` line; pass
`--artifact PATH` to also write the full result set to a JSON file.
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import sys
import time

REPO = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, REPO)


def make_batch(n: int, seed: int = 7):
    """n valid (message, key, signature) triples over 32-byte messages."""
    import hashlib

    from narwhal_tpu.crypto import KeyPair
    from narwhal_tpu.crypto.keys import cpu_verify

    from narwhal_tpu.crypto.digest import Digest

    kp = KeyPair.generate(rng_seed=hashlib.sha256(b"bench%d" % seed).digest())
    msgs = [hashlib.sha256(i.to_bytes(8, "little")).digest() for i in range(n)]
    # KeyPair.sign signs a Digest (32 bytes) — exactly the protocol's usage.
    sigs = [kp.sign(Digest(m)) for m in msgs]
    assert cpu_verify(msgs[0], kp.name, sigs[0])
    return msgs, [kp.name] * n, sigs


def bench_cpu(msgs, keys, sigs, budget_s: float = 2.0) -> float:
    """Single-core OpenSSL verifies/sec."""
    from narwhal_tpu.crypto.keys import cpu_verify

    n, i, t0 = 0, 0, time.perf_counter()
    while time.perf_counter() - t0 < budget_s:
        assert cpu_verify(msgs[i], keys[i], sigs[i])
        i = (i + 1) % len(msgs)
        n += 1
    return n / (time.perf_counter() - t0)


def bench_tpu(msgs, keys, sigs, batch: int, iters: int, pipeline_depth: int = 4):
    import numpy as np

    import jax.numpy as jnp

    from narwhal_tpu.ops import ed25519 as E

    m, k, s = msgs[:batch], keys[:batch], sigs[:batch]

    # Host prep cost (amortized per signature).
    t0 = time.perf_counter()
    args = E.prepare_batch(m, k, s, batch)
    prep_s = time.perf_counter() - t0
    jargs = [jnp.asarray(a) for a in args]

    # Warmup / compile (persistent cache makes this fast on reruns).
    t0 = time.perf_counter()
    mask = np.asarray(E._verify_kernel(*jargs))
    compile_s = time.perf_counter() - t0
    if not mask.all():
        raise AssertionError("kernel rejected valid signatures")

    # Blocking latency per batch.
    lat = []
    for _ in range(iters):
        t0 = time.perf_counter()
        np.asarray(E._verify_kernel(*jargs))
        lat.append(time.perf_counter() - t0)
    lat_s = statistics.median(lat)

    # Pipelined: dispatch K batches, block once at the end.
    t0 = time.perf_counter()
    outs = [E._verify_kernel(*jargs) for _ in range(pipeline_depth)]
    for o in outs:
        o.block_until_ready()
    pipe_s = (time.perf_counter() - t0) / pipeline_depth

    return {
        "batch": batch,
        "prep_us_per_sig": round(1e6 * prep_s / batch, 2),
        "compile_or_cache_load_s": round(compile_s, 2),
        "device_ms_per_batch": round(1e3 * lat_s, 2),
        "device_verifies_per_s": round(batch / lat_s, 1),
        "pipelined_verifies_per_s": round(batch / pipe_s, 1),
    }


def make_quorum(quorum: int, seed: int = 11):
    """quorum distinct keypairs all voting over ONE 32-byte digest —
    the exact shape certificate sanitization verifies."""
    import hashlib

    from narwhal_tpu.crypto import KeyPair
    from narwhal_tpu.crypto.digest import Digest

    msg = hashlib.sha256(b"cert-agg-%d-%d" % (quorum, seed)).digest()
    kps = [
        KeyPair.generate(
            rng_seed=hashlib.sha256(b"agg%d:%d" % (seed, i)).digest()
        )
        for i in range(quorum)
    ]
    votes = [(kp.name, kp.sign(Digest(msg))) for kp in kps]
    return msg, votes


def bench_aggregate(quorum: int, iters: int, batched: bool = False) -> dict:
    """The certificate-sanitization cost ladder at one quorum size:
    2f+1 serial CPU verifies (the `individual` scheme) vs ONE half-agg
    multiexp equation (`halfagg`) vs the batched-window device kernel
    over the same 2f+1 claims.  Oracle-checked before timing: the valid
    aggregate must verify and a bit-flipped / truncated / wrong-subset
    aggregate must not — a benchmark that times a verifier that accepts
    garbage measures nothing."""
    import statistics as stats

    from narwhal_tpu.crypto.aggregate import (
        aggregate_votes,
        cert_sig_wire_bytes,
        verify_halfagg,
    )
    from narwhal_tpu.crypto.keys import cpu_verify

    msg, votes = make_quorum(quorum)
    signers, agg = aggregate_votes(msg, votes)
    publics = [bytes(s) for s in signers]

    # Oracle: accept the real thing, reject the mutations.
    assert verify_halfagg(msg, publics, agg), "valid aggregate rejected"
    flipped = bytearray(agg)
    flipped[0] ^= 1
    assert not verify_halfagg(msg, publics, bytes(flipped)), (
        "bit-flipped aggregate accepted"
    )
    assert not verify_halfagg(msg, publics, bytes(agg)[:-32]), (
        "truncated aggregate accepted"
    )
    assert not verify_halfagg(msg, publics[:-1], agg), (
        "wrong-subset aggregate accepted"
    )
    by_key = {bytes(name): (name, sig) for name, sig in votes}
    ordered = [by_key[p] for p in publics]
    ordered_keys = [name for name, _ in ordered]
    ordered_sigs = [sig for _, sig in ordered]
    assert all(
        cpu_verify(msg, name, sig) for name, sig in votes
    ), "valid vote rejected by serial verifier"

    serial = []
    for _ in range(iters):
        t0 = time.perf_counter()
        ok = all(
            cpu_verify(msg, k, s)
            for k, s in zip(ordered_keys, ordered_sigs)
        )
        serial.append(time.perf_counter() - t0)
        assert ok
    agg_lat = []
    for _ in range(iters):
        t0 = time.perf_counter()
        ok = verify_halfagg(msg, publics, agg)
        agg_lat.append(time.perf_counter() - t0)
        assert ok
    out = {
        "quorum": quorum,
        "committee": {3: 4, 14: 20, 34: 50}.get(quorum),
        "serial_2f1_ms": round(1e3 * stats.median(serial), 3),
        "halfagg_verify_ms": round(1e3 * stats.median(agg_lat), 3),
        "halfagg_vs_serial": round(
            stats.median(agg_lat) / stats.median(serial), 3
        ),
        "verify_ops_per_cert": {"individual": quorum, "halfagg": 1},
        "sig_wire_bytes_v2": {
            "individual": cert_sig_wire_bytes("individual", quorum),
            "halfagg": cert_sig_wire_bytes("halfagg", quorum),
        },
    }

    # Batched-window arm: the device kernel over the same 2f+1 claims
    # (the verify-window pipeline's dispatch shape).  Opt-in
    # (--agg-batched): the first kernel call per shape pays an XLA
    # compile (minutes on a cold CPU host), and the ladder's
    # serial/aggregate legs are pure-Python and must not require a jax
    # install — CI passes the flag where tier-1's test_ed25519 pass has
    # already warmed the in-job compile cache.
    if not batched:
        out["batched_window_ms"] = None
        out["batched_window_skipped"] = "pass --agg-batched to enable"
        return out
    try:
        import numpy as np

        import jax.numpy as jnp

        from narwhal_tpu.ops import ed25519 as E

        msgs = [msg] * quorum
        jargs = [
            jnp.asarray(a)
            for a in E.prepare_batch(msgs, ordered_keys, ordered_sigs, quorum)
        ]
        mask = np.asarray(E._verify_kernel(*jargs))  # warmup / compile
        assert mask.all(), "batched kernel rejected valid quorum"
        batched = []
        for _ in range(iters):
            t0 = time.perf_counter()
            np.asarray(E._verify_kernel(*jargs))
            batched.append(time.perf_counter() - t0)
        out["batched_window_ms"] = round(1e3 * stats.median(batched), 3)
    except Exception as e:  # no jax / no device — ladder stays 2-leg
        out["batched_window_ms"] = None
        out["batched_window_skipped"] = f"{type(e).__name__}: {e}"
    return out


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument(
        "--batches", type=int, nargs="+", default=[128, 512, 2048, 8192]
    )
    ap.add_argument("--iters", type=int, default=5)
    ap.add_argument("--cpu-budget", type=float, default=2.0)
    ap.add_argument("--artifact", type=str, default=None)
    ap.add_argument(
        "--agg-quorums",
        type=int,
        nargs="+",
        default=None,
        help="Also run the certificate-aggregate ladder (serial 2f+1 vs "
        "one half-agg equation vs batched window) at these quorum sizes "
        "(3/14/34 = committees of 4/20/50).",
    )
    ap.add_argument(
        "--agg-only",
        action="store_true",
        help="Run ONLY the aggregate ladder (no TPU batch sweep) — the "
        "CI shape; defaults --agg-quorums to 3 14 34.",
    )
    ap.add_argument(
        "--agg-batched",
        action="store_true",
        help="Include the batched-window device-kernel leg in the "
        "aggregate ladder (pays an XLA compile per quorum shape when "
        "the persistent cache is cold).",
    )
    args = ap.parse_args()
    if args.agg_only and args.agg_quorums is None:
        args.agg_quorums = [3, 14, 34]

    if args.agg_only:
        results = {
            "metric": "cert_aggregate_verify_ladder",
            "aggregate": [],
        }
        for q in args.agg_quorums:
            r = bench_aggregate(q, args.iters, batched=args.agg_batched)
            results["aggregate"].append(r)
            print(json.dumps(r))
        if args.artifact:
            with open(args.artifact, "w") as f:
                json.dump(results, f, indent=2)
        return

    msgs, keys, sigs = make_batch(max(args.batches))

    cpu_vps = bench_cpu(msgs, keys, sigs, args.cpu_budget)
    from narwhal_tpu.ops import field25519 as F

    results = {
        "metric": "ed25519_verifies_per_sec_chip",
        "lane_dtype": "float32" if F.FP else "int32",
        "cpu_openssl_verifies_per_s_core": round(cpu_vps, 1),
        "host_cores": os.cpu_count(),
        "tpu": [],
    }
    import jax

    results["device"] = str(jax.devices()[0])
    for b in args.batches:
        r = bench_tpu(msgs, keys, sigs, b, args.iters)
        results["tpu"].append(r)
        print(json.dumps(r))

    if args.agg_quorums:
        results["aggregate"] = []
        for q in args.agg_quorums:
            r = bench_aggregate(q, args.iters, batched=args.agg_batched)
            results["aggregate"].append(r)
            print(json.dumps(r))

    best = max(results["tpu"], key=lambda r: r["pipelined_verifies_per_s"])
    results["best_verifies_per_s_chip"] = best["pipelined_verifies_per_s"]
    results["best_batch"] = best["batch"]
    results["vs_cpu_core"] = round(
        best["pipelined_verifies_per_s"] / cpu_vps, 2
    )
    print(
        json.dumps(
            {
                "metric": "ed25519_verifies_per_sec_chip",
                "value": results["best_verifies_per_s_chip"],
                "unit": "verifies/s",
                "lane_dtype": results["lane_dtype"],
                "vs_baseline": results["vs_cpu_core"],
                "cpu_core_verifies_per_s": results[
                    "cpu_openssl_verifies_per_s_core"
                ],
                "batch": best["batch"],
                "device": results["device"],
            }
        )
    )
    if args.artifact:
        with open(args.artifact, "w") as f:
            json.dump(results, f, indent=2)


if __name__ == "__main__":
    main()
