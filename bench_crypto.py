#!/usr/bin/env python3
"""North-star microbenchmark: ed25519 signature verifications/sec/chip.

BASELINE.json names `ed25519 verifies/sec/chip` as this build's own metric.
This benchmark measures the TPU batch verifier (narwhal_tpu/ops/ed25519.py,
the device analog of the reference's dalek `verify_batch`,
/root/reference/crypto/src/lib.rs:206-219) against the CPU/OpenSSL verifier
on the same host, at batch sizes spanning the protocol's realistic range
(a 4-node certificate carries 3 sigs; a 50-node round can burst ~8k sigs
through the Core's accumulate→batch-verify seam).

Methodology:
- steady state only: first call per shape compiles (tens of seconds, then
  cached persistently via NARWHAL_JAX_CACHE); timings start after a warmup
  call per shape.
- `device`: median-of-N wall time of dispatch→block on the result mask —
  the latency a Core burst actually pays.
- `pipelined`: K batches dispatched back-to-back before blocking — the
  sustained chip rate when host prep overlaps device compute (the async
  verify path in primary/core.py works this way).
- `prep`: host-side bytes→limbs/windows + SHA-512 hash-to-scalar cost.
- CPU baseline: single-core OpenSSL verify loop (this host has 1 core;
  multiply by core count for a multi-core host figure).

Output: one JSON line per configuration plus a `summary` line; pass
`--artifact PATH` to also write the full result set to a JSON file.
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import sys
import time

REPO = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, REPO)


def make_batch(n: int, seed: int = 7):
    """n valid (message, key, signature) triples over 32-byte messages."""
    import hashlib

    from narwhal_tpu.crypto import KeyPair
    from narwhal_tpu.crypto.keys import cpu_verify

    from narwhal_tpu.crypto.digest import Digest

    kp = KeyPair.generate(rng_seed=hashlib.sha256(b"bench%d" % seed).digest())
    msgs = [hashlib.sha256(i.to_bytes(8, "little")).digest() for i in range(n)]
    # KeyPair.sign signs a Digest (32 bytes) — exactly the protocol's usage.
    sigs = [kp.sign(Digest(m)) for m in msgs]
    assert cpu_verify(msgs[0], kp.name, sigs[0])
    return msgs, [kp.name] * n, sigs


def bench_cpu(msgs, keys, sigs, budget_s: float = 2.0) -> float:
    """Single-core OpenSSL verifies/sec."""
    from narwhal_tpu.crypto.keys import cpu_verify

    n, i, t0 = 0, 0, time.perf_counter()
    while time.perf_counter() - t0 < budget_s:
        assert cpu_verify(msgs[i], keys[i], sigs[i])
        i = (i + 1) % len(msgs)
        n += 1
    return n / (time.perf_counter() - t0)


def bench_tpu(msgs, keys, sigs, batch: int, iters: int, pipeline_depth: int = 4):
    import numpy as np

    import jax.numpy as jnp

    from narwhal_tpu.ops import ed25519 as E

    m, k, s = msgs[:batch], keys[:batch], sigs[:batch]

    # Host prep cost (amortized per signature).
    t0 = time.perf_counter()
    args = E.prepare_batch(m, k, s, batch)
    prep_s = time.perf_counter() - t0
    jargs = [jnp.asarray(a) for a in args]

    # Warmup / compile (persistent cache makes this fast on reruns).
    t0 = time.perf_counter()
    mask = np.asarray(E._verify_kernel(*jargs))
    compile_s = time.perf_counter() - t0
    if not mask.all():
        raise AssertionError("kernel rejected valid signatures")

    # Blocking latency per batch.
    lat = []
    for _ in range(iters):
        t0 = time.perf_counter()
        np.asarray(E._verify_kernel(*jargs))
        lat.append(time.perf_counter() - t0)
    lat_s = statistics.median(lat)

    # Pipelined: dispatch K batches, block once at the end.
    t0 = time.perf_counter()
    outs = [E._verify_kernel(*jargs) for _ in range(pipeline_depth)]
    for o in outs:
        o.block_until_ready()
    pipe_s = (time.perf_counter() - t0) / pipeline_depth

    return {
        "batch": batch,
        "prep_us_per_sig": round(1e6 * prep_s / batch, 2),
        "compile_or_cache_load_s": round(compile_s, 2),
        "device_ms_per_batch": round(1e3 * lat_s, 2),
        "device_verifies_per_s": round(batch / lat_s, 1),
        "pipelined_verifies_per_s": round(batch / pipe_s, 1),
    }


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument(
        "--batches", type=int, nargs="+", default=[128, 512, 2048, 8192]
    )
    ap.add_argument("--iters", type=int, default=5)
    ap.add_argument("--cpu-budget", type=float, default=2.0)
    ap.add_argument("--artifact", type=str, default=None)
    args = ap.parse_args()

    msgs, keys, sigs = make_batch(max(args.batches))

    cpu_vps = bench_cpu(msgs, keys, sigs, args.cpu_budget)
    from narwhal_tpu.ops import field25519 as F

    results = {
        "metric": "ed25519_verifies_per_sec_chip",
        "lane_dtype": "float32" if F.FP else "int32",
        "cpu_openssl_verifies_per_s_core": round(cpu_vps, 1),
        "host_cores": os.cpu_count(),
        "tpu": [],
    }
    import jax

    results["device"] = str(jax.devices()[0])
    for b in args.batches:
        r = bench_tpu(msgs, keys, sigs, b, args.iters)
        results["tpu"].append(r)
        print(json.dumps(r))

    best = max(results["tpu"], key=lambda r: r["pipelined_verifies_per_s"])
    results["best_verifies_per_s_chip"] = best["pipelined_verifies_per_s"]
    results["best_batch"] = best["batch"]
    results["vs_cpu_core"] = round(
        best["pipelined_verifies_per_s"] / cpu_vps, 2
    )
    print(
        json.dumps(
            {
                "metric": "ed25519_verifies_per_sec_chip",
                "value": results["best_verifies_per_s_chip"],
                "unit": "verifies/s",
                "lane_dtype": results["lane_dtype"],
                "vs_baseline": results["vs_cpu_core"],
                "cpu_core_verifies_per_s": results[
                    "cpu_openssl_verifies_per_s_core"
                ],
                "batch": best["batch"],
                "device": results["device"],
            }
        )
    )
    if args.artifact:
        with open(args.artifact, "w") as f:
            json.dump(results, f, indent=2)


if __name__ == "__main__":
    main()
